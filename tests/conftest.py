"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in this environment; sharding tests
run against ``--xla_force_host_platform_device_count=8`` CPU devices instead
(the driver separately dry-run-compiles the multi-chip path via
``__graft_entry__.dryrun_multichip``). Must be set before jax is imported.
"""

import os
import sys

# Unconditional: this environment exports JAX_PLATFORMS=axon (the real TPU
# tunnel); tests must never land on the single real chip. The env var alone
# is NOT enough — pytest plugins can import jax before this conftest runs,
# by which point jax.config has already read the environment — so the
# platform is also forced through jax.config below.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The bit-exact Go-PRNG path needs 64-bit integers under jit. The env-var
# route (JAX_ENABLE_X64) is unreliable here because the environment's TPU
# plugin can initialize jax.config before test code runs; the programmatic
# switch always works.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# NOTE: do NOT enable jax_compilation_cache_dir here. XLA:CPU executable
# deserialization segfaults on this jaxlib (hard crash mid-suite in a
# cache-hit pjit call), so the persistent compile cache is a correctness
# hazard on the CPU mesh, not a speedup.

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh, not the real TPU chip")
assert len(jax.devices()) >= 8, (
    "xla_force_host_platform_device_count=8 did not take effect "
    "(XLA backends were initialized before conftest ran?)")


def pytest_configure(config):
    # the tier-1 gate runs -m 'not slow' (ROADMAP.md): anything beyond the
    # ~30s-per-test budget carries this marker and runs only in full passes
    config.addinivalue_line(
        "markers", "slow: exceeds the tier-1 time budget "
                   "(deselected by -m 'not slow')")


import pytest  # noqa: E402


@pytest.fixture(scope="session")
def ring8_sync_stream_runner():
    """ONE compiled ring-8 sync streaming runner shared across test files
    (test_stream.py, test_memo.py): both drive the identical (topology,
    config, delay, batch) shape, and the jitted stream step is among the
    most expensive compiles in the tier-1 gate — module-scoped copies
    paid it once per file. Runner jit caches live on the instance, so
    sharing the instance is what shares the compile. Tests must not
    mutate the runner (memo/memo_cache arms build their own)."""
    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import ring_topology
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    return BatchedRunner(
        ring_topology(8), SimConfig.for_workload(snapshots=4,
                                                 max_recorded=128),
        make_fast_delay("hash", 11), 4, scheduler="sync")


@pytest.fixture(scope="session")
def fused_pair10():
    """ONE split/fused TickKernel pair on the loaded strongly-connected
    10-node graph, shared across the fused-megatick differentials
    (tests/test_megatick_fused.py): the fused arm's interpret-mode
    Pallas compile is among the heaviest in the tier-1 gate, and every
    differential drives the identical (topology, config, delay) shape —
    per-test copies would pay it once per test. Jit caches live on the
    kernel instances, so sharing the instances shares the compiles.
    Returns ``(kern_split, kern_fused, state)``: both kernels are
    cascade/gather/megatick=4 (kernel_engine=pallas so the SPLIT arm
    exercises the per-stage kernels too; fused_block_edges=5 forces
    multi-block DMA geometry on the 21-edge graph), ``state`` carries
    live traffic plus one snapshot in flight. Tests must not mutate the
    kernels or the state (run/drain return fresh pytrees; arms needing
    other knobs build their own)."""
    import random

    import numpy as np

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.core.state import DenseTopology, init_state
    from chandy_lamport_tpu.ops.delay_jax import HashJaxDelay
    from chandy_lamport_tpu.ops.tick import TickKernel
    from chandy_lamport_tpu.utils.randgen import random_strongly_connected

    topo = DenseTopology(random_strongly_connected(random.Random(11), 10))
    cfg = SimConfig(max_snapshots=4, queue_capacity=32, max_recorded=64)
    delay = HashJaxDelay(seed=7)
    kern_split = TickKernel(topo, cfg, delay, exact_impl="cascade",
                            megatick=4, kernel_engine="pallas",
                            fused_tick="off")
    kern_fused = TickKernel(topo, cfg, delay, exact_impl="cascade",
                            megatick=4, kernel_engine="pallas",
                            fused_tick="on", fused_block_edges=5)
    s = init_state(topo, cfg, delay.init_state())
    for e in range(0, topo.e, 3):
        s = kern_split.inject_send(s, np.int32(e), np.int32(2))
    s = kern_split.inject_snapshot(s, np.int32(0))
    # host-side: run_ticks/drain_and_flush donate their state argument,
    # which would delete a shared device-resident fixture on first use
    return kern_split, kern_fused, jax.device_get(s)


@pytest.fixture(scope="session")
def batched8_default_ref():
    """The auto-layouts battery's shared reference arm: ONE default-layout
    (row-major) runner on the 8nodes golden topology plus its phases-6
    storm run, compiled and executed once for the whole session. Every
    test in the battery needs these same reference bits to prove the
    auto_layouts mechanism changes layouts, never values — each used to
    rebuild the runner and re-pay the ~4 s storm compile. Returns
    ``(ref_runner, prog, ref_final)`` with ``ref_final`` on the host.
    Tests must not mutate the runner (the auto=True arms under test
    build their own); running other programs through it is fine — that
    is the point, its jit caches accumulate on the instance."""
    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import storm_program
    from chandy_lamport_tpu.ops.delay_jax import UniformJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.fixtures import read_topology_file
    from chandy_lamport_tpu.utils.goldens import fixture_path

    topo_spec = read_topology_file(fixture_path("8nodes.top"))
    runner = BatchedRunner(topo_spec, SimConfig(), UniformJaxDelay(seed=3),
                           batch=4, scheduler="sync", auto_layouts=False)
    prog = storm_program(runner.topo, phases=6, amount=1,
                         snapshot_phases=[(0, 0), (2, 4)])
    final = jax.device_get(runner.run_storm(runner.init_batch_device(), prog))
    return runner, prog, final
