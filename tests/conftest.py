"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in this environment; sharding tests
run against ``--xla_force_host_platform_device_count=8`` CPU devices instead
(the driver separately dry-run-compiles the multi-chip path via
``__graft_entry__.dryrun_multichip``). Must be set before jax is imported.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# The bit-exact Go-PRNG path needs 64-bit integers under jit.
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
