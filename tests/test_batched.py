"""Batched (vmap) execution tests: script compilation, lane-equivalence with
the single-instance dense backend, per-lane invariants under independent
delay streams, and sharded-vs-unsharded equality on the virtual 8-device
CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chandy_lamport_tpu.api import run_events
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import DenseTopology, decode_snapshot
from chandy_lamport_tpu.models.delay import FixedDelay, GoExactDelay
from chandy_lamport_tpu.ops.delay_jax import (
    FixedJaxDelay,
    GoExactJaxDelay,
    UniformJaxDelay,
)
from chandy_lamport_tpu.parallel.batch import (
    OP_SEND,
    OP_SNAPSHOT,
    BatchedRunner,
    compile_events,
)
from chandy_lamport_tpu.parallel.mesh import instance_mesh, replicate, shard_batch
from chandy_lamport_tpu.utils.fixtures import read_events_file, read_topology_file
from chandy_lamport_tpu.utils.goldens import fixture_path


def _lane(host_state, i):
    return jax.tree_util.tree_map(lambda x: x[i], host_state)


def _fixture(top, events):
    return (read_topology_file(fixture_path(top)),
            read_events_file(fixture_path(events)))


def test_compile_events_shapes_and_order():
    topo_spec, events = _fixture("3nodes.top", "3nodes-simple.events")
    topo = DenseTopology(topo_spec)
    script = compile_events(topo, events)
    kind = np.asarray(script.kind)
    # ops preserve script order within a phase; every phase ends in a tick
    assert kind.ndim == 2
    assert set(np.unique(kind)) <= {0, OP_SEND, OP_SNAPSHOT}
    # the fixture has sends and one snapshot
    assert (kind == OP_SEND).sum() >= 1
    assert (kind == OP_SNAPSHOT).sum() == 1


def test_batched_lanes_match_single_instance_goexact():
    """B lanes sharing the reference's Go-exact stream must each reproduce
    the single-instance DenseSim result exactly."""
    topo_spec, events = _fixture("3nodes.top", "3nodes-simple.events")
    single_snaps, single_sim = run_events("jax", topo_spec, events,
                                          GoExactDelay(4242))

    runner = BatchedRunner(topo_spec, SimConfig(), GoExactJaxDelay(4242), batch=4)
    script = compile_events(runner.topo, events)
    final = runner.run(runner.init_batch(), script)
    host = jax.device_get(final)

    assert int(host.error.sum()) == 0
    for i in range(4):
        lane = _lane(host, i)
        snap = decode_snapshot(runner.topo, lane, 0)
        assert snap.token_map == single_snaps[0].token_map
        assert snap.messages == single_snaps[0].messages
        assert ({nid: int(lane.tokens[j]) for j, nid in enumerate(runner.topo.ids)}
                == single_sim.node_tokens())


@pytest.mark.slow  # ~9 s; the goexact leg above keeps batched-vs-single in tier-1
def test_batched_lanes_match_single_instance_fixed_delay():
    topo_spec, events = _fixture("2nodes.top", "2nodes-message.events")
    single_snaps, _ = run_events("jax", topo_spec, events, FixedDelay(2))
    runner = BatchedRunner(topo_spec, SimConfig(), FixedJaxDelay(2), batch=3)
    script = compile_events(runner.topo, events)
    host = jax.device_get(runner.run(runner.init_batch(), script))
    for i in range(3):
        snap = decode_snapshot(runner.topo, _lane(host, i), 0)
        assert snap.token_map == single_snaps[0].token_map
        assert snap.messages == single_snaps[0].messages


@pytest.mark.slow  # conservation is asserted by every tier-1 storm summary
def test_independent_streams_conserve_tokens_per_lane():
    """UniformJaxDelay gives each lane its own stream: schedules diverge but
    every lane must satisfy the conservation invariant
    (test_common.go:298-328) for every completed snapshot."""
    topo_spec, events = _fixture("10nodes.top", "10nodes.events")
    b = 8
    runner = BatchedRunner(topo_spec, SimConfig(queue_capacity=32),
                           UniformJaxDelay(seed=99), batch=b)
    script = compile_events(runner.topo, events)
    host = jax.device_get(runner.run(runner.init_batch(), script))

    assert int(host.error.sum()) == 0
    total0 = int(runner.topo.tokens0.sum())
    n = runner.topo.n
    lanes_diverged = False
    for i in range(b):
        lane = _lane(host, i)
        # all queues drained, so conservation is against live balances
        assert int(lane.q_len.sum()) == 0
        assert int(lane.tokens.sum()) == total0
        for sid in range(int(lane.next_sid)):
            assert int(lane.completed[sid]) == n
            snap = decode_snapshot(runner.topo, lane, sid)
            frozen = sum(snap.token_map.values())
            recorded = sum(m.message.data for m in snap.messages)
            assert frozen + recorded == total0
        # final balances are schedule-independent here (every node sends and
        # receives the same totals), but what a snapshot FREEZES is schedule
        # sensitive — that's where independent streams must show up
        if i and not np.array_equal(lane.frozen, host.frozen[0]):
            lanes_diverged = True
    assert lanes_diverged  # streams actually differ across lanes


@pytest.mark.slow  # ~11 s; test_prepare_storm_births_state_in_compiled_formats
# keeps the AUTO compile path + formats feedback + bit-identity in tier-1
def test_auto_layouts_matches_default(batched8_default_ref):
    """The bench's --layouts auto path (XLA-chosen jit-boundary layouts,
    VERDICT r4 #6): a storm run under auto_layouts + the state_formats ->
    init_batch_device(formats=...) feedback must be bit-identical to the
    row-major default. Identity on CPU layouts-wise, but this pins the
    whole mechanism (AUTO jits accept jit-built states, the formats
    builder emits a consumable state, values unchanged)."""
    ref_runner, prog, ref = batched8_default_ref
    assert ref_runner.storm_state_formats() is None  # default mode: none

    topo_spec, _ = _fixture("8nodes.top", "8nodes-sequential-snapshots.events")
    runner = BatchedRunner(topo_spec, SimConfig(), UniformJaxDelay(seed=3),
                           batch=4, scheduler="sync", auto_layouts=True)
    final = runner.run_storm(runner.init_batch_device(), prog)
    fmts = runner.storm_state_formats()
    assert fmts is not None
    # second dispatch from a formats-built fresh state (the bench's
    # timed-repeat shape)
    final = runner.run_storm(runner.init_batch_device(formats=fmts), prog)
    for leaf_d, leaf_a in zip(jax.tree_util.tree_leaves(ref),
                              jax.tree_util.tree_leaves(
                                  jax.device_get(final))):
        np.testing.assert_array_equal(np.asarray(leaf_d), np.asarray(leaf_a))


def test_auto_layout_rejection_falls_back(batched8_default_ref):
    """If the AOT executable rejects the ``input_formats``-derived layouts
    at call time (observed on the axon TPU tunnel, where ``input_formats``
    can disagree with the executable's true parameter layouts), the runner
    must degrade permanently to the row-major jit path, produce the same
    bits, and report the degradation via ``layouts_effective``."""
    topo_spec, _ = _fixture("8nodes.top", "8nodes-sequential-snapshots.events")
    _, prog, ref = batched8_default_ref

    runner = BatchedRunner(topo_spec, SimConfig(), UniformJaxDelay(seed=3),
                           batch=4, scheduler="sync", auto_layouts=True)
    state = runner.init_batch_device()
    progj = tuple(jnp.asarray(x) for x in prog)

    from chandy_lamport_tpu.utils.layouts import array_format

    class RejectingComp:
        """Stands in for the compiled storm: formats that match the live
        arrays (so the relayout dispatch is skipped) but a call-time
        layout error."""
        input_formats = (jax.tree_util.tree_map(
            array_format, (state, progj)), {})

        def __call__(self, *a):
            raise ValueError(
                "Computation was compiled for input layouts that disagree "
                "with the layouts of arguments passed to it.")

    key = (True, tuple((tuple(x.shape), str(x.dtype)) for x in progj))
    runner._storm_aot[key] = (RejectingComp(), lambda s, p: (s, p))
    # sentinel: the fallback must reset this (bench would otherwise build
    # timed states in the rejected layouts) and drop the dead executable
    runner._storm_state_formats = object()
    assert runner.layouts_effective == "auto"
    with pytest.warns(UserWarning, match="falling back"):
        final = runner.run_storm(state, prog)
    assert runner.layouts_effective == "default(auto-rejected)"
    assert runner.storm_state_formats() is None
    assert not runner._storm_aot
    for leaf_r, leaf_f in zip(jax.tree_util.tree_leaves(ref),
                              jax.tree_util.tree_leaves(jax.device_get(final))):
        np.testing.assert_array_equal(np.asarray(leaf_r), np.asarray(leaf_f))
    # subsequent runs skip the AOT path entirely (no second warning)
    final2 = runner.run_storm(runner.init_batch_device(), prog)
    assert runner.layouts_effective == "default(auto-rejected)"
    jax.block_until_ready(final2)


@pytest.mark.slow  # per-key eviction also pinned by the serving exec-cache tests
def test_auto_layout_rejection_is_per_shape_bucket(batched8_default_ref):
    """A rejection evicts ONLY its own shape bucket: another program
    shape compiled earlier keeps its AOT executable (and the state
    formats feedback), and ``layouts_effective`` reports the partial
    degradation instead of a blanket fallback — a serving process must
    not re-pay every warm tenant's compile because one odd topology's
    layouts were refused."""
    from chandy_lamport_tpu.models.workloads import storm_program
    from chandy_lamport_tpu.utils.layouts import array_format

    topo_spec, _ = _fixture("8nodes.top", "8nodes-sequential-snapshots.events")
    ref_runner, prog_a, _ = batched8_default_ref
    runner = BatchedRunner(topo_spec, SimConfig(), UniformJaxDelay(seed=3),
                           batch=4, scheduler="sync", auto_layouts=True)
    prog_b = storm_program(runner.topo, phases=4, amount=1,
                           snapshot_phases=[(0, 0)])
    # bucket A: a real compile on the live AOT path
    jax.block_until_ready(
        runner.run_storm(runner.init_batch_device(), prog_a))
    key_a = (True, tuple((tuple(np.shape(x)), str(np.asarray(x).dtype))
                         for x in prog_a))
    assert key_a in runner._storm_aot and runner.layouts_effective == "auto"

    # prog_b's reference bits ride the shared default runner too (a new
    # jit-cache entry on its instance, not a mutation)
    ref_b = jax.device_get(
        ref_runner.run_storm(ref_runner.init_batch_device(), prog_b))

    state = runner.init_batch_device()
    progj_b = tuple(jnp.asarray(x) for x in prog_b)

    class RejectingComp:
        input_formats = (jax.tree_util.tree_map(
            array_format, (state, progj_b)), {})

        def __call__(self, *a):
            raise ValueError(
                "Computation was compiled for input layouts that disagree "
                "with the layouts of arguments passed to it.")

    key_b = (True, tuple((tuple(x.shape), str(x.dtype)) for x in progj_b))
    runner._storm_aot[key_b] = (RejectingComp(), lambda s, p: (s, p))
    with pytest.warns(UserWarning, match="falling back"):
        final_b = runner.run_storm(state, prog_b)
    # bucket B degraded, bucket A (and the formats feedback) survive
    assert runner.layouts_effective == "auto(+1 rejected)"
    assert key_a in runner._storm_aot and key_b not in runner._storm_aot
    assert runner.storm_state_formats() is not None
    for leaf_r, leaf_f in zip(jax.tree_util.tree_leaves(ref_b),
                              jax.tree_util.tree_leaves(
                                  jax.device_get(final_b))):
        np.testing.assert_array_equal(np.asarray(leaf_r), np.asarray(leaf_f))
    # bucket A still dispatches through its warm executable (no warning,
    # no recompile), and B's shape stays on the row-major jits silently
    final_a = runner.run_storm(runner.init_batch_device(), prog_a)
    jax.block_until_ready(final_a)
    assert runner.layouts_effective == "auto(+1 rejected)"
    final_b2 = runner.run_storm(runner.init_batch_device(), prog_b)
    jax.block_until_ready(final_b2)
    assert key_b not in runner._storm_aot


def test_prepare_storm_births_state_in_compiled_formats(batched8_default_ref):
    """prepare_storm compiles from shapes alone (no live state), and a
    state built via init_batch_device(formats=prepare_storm(...)) already
    matches the executable's input formats — the bench warmup relies on
    this to never pay a relayout dispatch or transient double residency."""
    from chandy_lamport_tpu.parallel.batch import _formats_match

    topo_spec, _ = _fixture("8nodes.top", "8nodes-sequential-snapshots.events")
    ref_runner, prog, ref = batched8_default_ref
    runner = BatchedRunner(topo_spec, SimConfig(), UniformJaxDelay(seed=3),
                           batch=4, scheduler="sync", auto_layouts=True)
    fmts0 = runner.prepare_storm(prog)
    assert fmts0 is not None
    state = runner.init_batch_device(formats=fmts0)
    assert _formats_match(state, fmts0)
    final = runner.run_storm(state, prog)
    assert runner.layouts_effective == "auto"

    # bit-identity with the shared default-layout runner
    assert ref_runner.prepare_storm(prog) is None  # default mode: no-op
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(jax.device_get(final))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # ~10 s; test_auto_layout_rejection_falls_back keeps the
# mismatched-layout degradation surface in tier-1 (CPU backends usually
# skip this test's premise anyway)
def test_relayout_branch_executes_on_mismatched_layouts():
    """Force a genuinely mismatched input layout (a column-major tokens
    plane) so run_storm's compiled-identity relayout branch actually
    executes, and assert the dispatch still succeeds with identical bits.
    On backends where device_put ignores the requested layout the
    premise can't be constructed — skip."""
    from chandy_lamport_tpu.models.workloads import storm_program
    from chandy_lamport_tpu.utils.layouts import (
        array_format,
        concrete_format,
        format_layout,
    )

    topo_spec, _ = _fixture("8nodes.top", "8nodes-sequential-snapshots.events")
    runner = BatchedRunner(topo_spec, SimConfig(), UniformJaxDelay(seed=3),
                           batch=4, scheduler="sync", auto_layouts=True)
    prog = storm_program(runner.topo, phases=6, amount=1,
                         snapshot_phases=[(0, 0), (2, 4)])
    ref = jax.device_get(
        runner.run_storm(runner.init_batch_device(), prog))

    state = runner.init_batch_device()
    cur = array_format(state.tokens)
    flipped = concrete_format(
        tuple(reversed(format_layout(cur).major_to_minor)), cur.sharding)
    try:
        moved = jax.device_put(state.tokens, flipped)
    except Exception:  # XLA:CPU on some jax builds refuses non-default
        pytest.skip("backend cannot produce non-default layouts")
    if format_layout(array_format(moved)) == format_layout(cur):
        pytest.skip("backend ignores device_put layout requests")
    final = jax.device_get(
        runner.run_storm(state._replace(tokens=moved), prog))
    assert runner.layouts_effective == "auto"
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(final)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # graphshard equality stays tier-1 via test_graphshard_script
def test_sharded_run_matches_unsharded():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual CPU devices"
    topo_spec, events = _fixture("8nodes.top", "8nodes-sequential-snapshots.events")
    b = 16
    runner = BatchedRunner(topo_spec, SimConfig(), UniformJaxDelay(seed=7), batch=b)
    script = compile_events(runner.topo, events)

    plain = jax.device_get(runner.run(runner.init_batch(), script))

    mesh = instance_mesh(8)
    state = shard_batch(runner.init_batch(), mesh)
    sharded = jax.device_get(runner.run(state, replicate(script, mesh)))

    for leaf_p, leaf_s in zip(jax.tree_util.tree_leaves(plain),
                              jax.tree_util.tree_leaves(sharded)):
        np.testing.assert_array_equal(np.asarray(leaf_p), np.asarray(leaf_s))

    summary = BatchedRunner.summarize(jax.device_put(sharded))
    assert summary["instances"] == b
    assert summary["error_lanes"] == 0
    assert summary["snapshots_started"] == 2 * b
    assert summary["snapshots_completed"] == 2 * b
