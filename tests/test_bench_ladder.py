"""Unit tests for the bench orchestrator's fallback-ladder gating.

The ladder (chandy_lamport_tpu/bench.py main) is subprocess-driven in
production; here ``_spawn`` is monkeypatched to script failure sequences
so the gate semantics — which attempt runs after which failure kind —
are pinned without any device or subprocess. These gates were
hand-verified against a live wedged tunnel (round 5); the tests keep
them from regressing silently.

What the ladder models at measurement time: giving the reference hot
loop's TPU measurement (/root/reference/chandy_lamport/sim.go:71-95)
every realistic shot at the device before conceding a labeled fallback.
"""

import json

import pytest

from chandy_lamport_tpu import bench


class ScriptedSpawn:
    """Replaces bench._spawn: returns scripted outcomes per attempt name
    and records the order of attempts."""

    def __init__(self, outcomes):
        # name -> (parsed|None, timed_out, retryable, backend_init)
        self.outcomes = outcomes
        self.calls = []

    def __call__(self, name, mode, env_overrides, extra, timeout, argv):
        self.calls.append(name)
        if name not in self.outcomes:
            pytest.fail(f"unscripted attempt {name!r} (ran {self.calls})")
        return self.outcomes[name]


OK = ({"metric": "node_ticks_per_sec_per_chip", "value": 1.0,
       "platform": "tpu"}, False, False, False)
HANG = (None, True, True, False)
SIGNAL_DEATH = (None, False, True, False)      # rc in (-6, -9, -11)
BACKEND_INIT = (None, False, True, True)       # clean EXIT_BACKEND_INIT
CLEAN_FAIL = (None, False, False, False)       # deterministic rc=1


def run_main(monkeypatch, capsys, argv, outcomes, platform="tpu",
             dead_platform=None):
    spawn = ScriptedSpawn(outcomes)
    monkeypatch.setattr(bench, "_spawn", spawn)
    monkeypatch.setattr(bench, "_find_live_platform",
                        lambda args: (platform, {}, dead_platform is not None,
                                      dead_platform))
    rc = bench.main(argv)
    assert rc == 0  # the orchestrator always exits 0 with one JSON line
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    return spawn.calls, json.loads(out[-1])


def test_probed_path_retries_after_signal_death(monkeypatch, capsys):
    # the round-5 review regression pin: a signal-killed full-size attempt
    # (transient OOM-kill / segfault) must still get the full-size retry,
    # exactly like a hang — not fall through to the clamped tpu-small row
    calls, row = run_main(
        monkeypatch, capsys, ["--timeout", "60"],
        {"default": SIGNAL_DEATH, "default-retry": OK})
    assert calls == ["default", "default-retry"]
    assert row["platform"] == "tpu"


def test_probed_path_no_retry_after_clean_failure(monkeypatch, capsys):
    # deterministic rc=1 (invalid results, repeated OOM at final capacity):
    # a same-size retry would fail identically and a clamped/CPU attempt
    # would mask the failure with a success-shaped number
    calls, row = run_main(
        monkeypatch, capsys, ["--timeout", "60"], {"default": CLEAN_FAIL})
    assert calls == ["default"]
    assert row["platform"] == "none" and "error" in row


def test_assume_tpu_success_is_single_attempt(monkeypatch, capsys):
    calls, row = run_main(
        monkeypatch, capsys, ["--assume-tpu", "--timeout", "60"],
        {"default": OK})
    assert calls == ["default"]
    assert row["platform"] == "tpu"


def test_assume_tpu_hang_skips_rescue_goes_cpu(monkeypatch, capsys):
    # a hang means the tunnel wedged: the CLSIM_PLATFORM=auto rescue would
    # hang identically, so the ladder must fall straight to the labeled
    # cpu row (one worker timeout + fallback, as documented)
    calls, row = run_main(
        monkeypatch, capsys, ["--assume-tpu", "--timeout", "60"],
        {"default": HANG,
         "cpu": ({"metric": "node_ticks_per_sec_per_chip", "value": 1.0,
                  "platform": "cpu"}, False, False, False)})
    assert calls == ["default", "cpu"]
    assert row["platform"] == "cpu"


def test_assume_tpu_backend_init_fires_auto_rescue(monkeypatch, capsys):
    # EXIT_BACKEND_INIT is the one failure CLSIM_PLATFORM=auto can fix
    # (the round-1 plugin-init failure) — the rescue must fire there
    calls, row = run_main(
        monkeypatch, capsys, ["--assume-tpu", "--timeout", "60"],
        {"default": BACKEND_INIT, "tpu-auto": OK})
    assert calls == ["default", "tpu-auto"]
    assert row["platform"] == "tpu"


def test_assume_tpu_signal_death_gets_same_env_retry(monkeypatch, capsys):
    # a transient signal death (OOM-kill / segfault) with a vouched-for
    # tunnel gets one same-env full-size retry, cheap via the compile
    # cache — matching the probed ladder's classification
    calls, row = run_main(
        monkeypatch, capsys, ["--assume-tpu", "--timeout", "60"],
        {"default": SIGNAL_DEATH, "default-retry": OK})
    assert calls == ["default", "default-retry"]
    assert row["platform"] == "tpu"


def test_assume_tpu_double_signal_death_goes_cpu(monkeypatch, capsys):
    # two signal deaths in a row: not transient — skip the auto rescue
    # (it is for plugin-init failures only) and bank the labeled cpu row
    calls, row = run_main(
        monkeypatch, capsys, ["--assume-tpu", "--timeout", "60"],
        {"default": SIGNAL_DEATH, "default-retry": SIGNAL_DEATH,
         "cpu": ({"metric": "node_ticks_per_sec_per_chip", "value": 1.0,
                  "platform": "cpu"}, False, False, False)})
    assert calls == ["default", "default-retry", "cpu"]
    assert row["platform"] == "cpu"


@pytest.mark.parametrize("engine", ["gather", "mask"])
def test_worker_row_round_trips_queue_engine(engine, capsys):
    """A real (tiny, CPU) --worker measurement: the JSON row must carry
    the queue_engine that actually ran, so BENCH_*.json rows attribute
    wins to the right ring addressing (PR-2 satellite)."""
    rc = bench.main(["--worker", "--nodes", "16", "--batch", "2",
                     "--phases", "3", "--snapshots", "2", "--repeats", "1",
                     "--queue-engine", engine])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert row["metric"] == "node_ticks_per_sec_per_chip"
    assert row["queue_engine"] == engine
    assert row["value"] > 0
    # snapshot-lifecycle stats round-trip on EVERY row (PR-4 satellite):
    # a clean supervised-off run reports zero churn, a live recovery line,
    # and no supervisor knobs (they only stamp the row when armed)
    lc = row["snapshot_lifecycle"]
    assert lc["completed"] == lc["initiated"] > 0
    assert lc["retried"] == lc["failed"] == lc["stale_markers"] == 0
    assert row["recovery_line_age"] == lc["recovery_line_age_max"] >= 0
    assert "snapshot_timeout" not in row
    # the analytic roofline rides every row (utils/metrics
    # .tick_cost_model) keyed to the engine that actually ran
    cm = row["cost_model"]
    assert cm["queue_engine"] == engine and cm["batch"] == 2
    assert cm["hbm_bytes_per_tick"] == 2 * cm["instance_bytes"] * 2
    assert cm["elem_ops_per_tick"] > 0


@pytest.mark.slow  # ~12 s; graphshard bit-identity stays tier-1 via
# test_graphshard_script, and the worker-row schema via the queue/kernel
# engine row tests above — this pins only the comm_engine stamp
def test_graphshard_worker_row_round_trips_comm_engine(capsys):
    """A real (tiny, CPU) graph-sharded --worker run: the row must carry
    the comm engine and megatick depth that actually ran plus the
    per-tick comm-bytes model, so a BENCH row measured under the sparse
    halo exchange can never masquerade as a dense-plane number."""
    rc = bench.main(["--worker", "--graphshard", "2", "--nodes", "16",
                     "--phases", "3", "--snapshots", "2", "--repeats", "1",
                     "--comm-engine", "sparse", "--megatick", "2"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert row["mode"] == "graphshard" and row["graphshard"] == 2
    assert row["comm_engine"] == "sparse"
    assert row["megatick"] == 2
    model = row["comm_bytes_model"]
    assert model["sparse_bytes_per_tick"] > 0
    assert model["dense_bytes_per_tick"] > 0
    assert model["sparse_over_dense"] == pytest.approx(
        model["sparse_bytes_per_tick"] / model["dense_bytes_per_tick"],
        rel=1e-3)


@pytest.mark.slow
def test_worker_row_round_trips_supervisor_knobs(capsys):
    """An armed-supervisor worker run stamps its knobs on the row, so a
    ladder rung measured under the supervisor can never masquerade as an
    unsupervised number (tier-1 already round-trips the lifecycle fields
    in the queue-engine rows above; the armed run rides full passes)."""
    rc = bench.main(["--worker", "--nodes", "16", "--batch", "2",
                     "--phases", "3", "--snapshots", "2", "--repeats", "1",
                     "--snapshot-timeout", "64"])
    assert rc == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["snapshot_timeout"] == 64
    assert row["snapshot_retries"] == 3
    lc = row["snapshot_lifecycle"]
    assert lc["completed"] == lc["initiated"] > 0 and lc["failed"] == 0


def test_dead_probe_path_tries_tpu_blind_then_cpu(monkeypatch, capsys):
    # every probe hung: one blind full-size TPU attempt before the cpu
    # fallback (the round-3 official number was lost to skipping this).
    # This is the GENERIC-hang path — no platform was positively
    # identified as unusable, so the tunnel may still recover mid-window
    calls, row = run_main(
        monkeypatch, capsys, ["--timeout", "60"],
        {"tpu-blind": HANG,
         "cpu": ({"metric": "node_ticks_per_sec_per_chip", "value": 1.0,
                  "platform": "cpu"}, False, False, False)},
        platform=None)
    assert calls == ["tpu-blind", "cpu"]
    assert row["platform"] == "cpu"


def test_unusable_platform_verdict_skips_tpu_blind(monkeypatch, capsys):
    # the probe watchdog positively identified a known-unusable platform
    # (UNUSABLE_PLATFORMS, e.g. the experimental axon plugin whose
    # jax.devices() hangs): the hang is structural, so the ladder must
    # fall straight to the labeled cpu row — no 600s tpu-blind burn
    # (the BENCH_r05 failure this satellite exists for)
    calls, row = run_main(
        monkeypatch, capsys, ["--timeout", "60"],
        {"cpu": ({"metric": "node_ticks_per_sec_per_chip", "value": 1.0,
                  "platform": "cpu"}, False, False, False)},
        platform=None, dead_platform="axon")
    assert calls == ["cpu"]
    assert row["platform"] == "cpu"


def _probe_args(tmp_path, **over):
    """A parsed-args namespace for _find_live_platform tests."""
    defaults = {"no_probe_cache": False, "probe_cache_ttl": 3600.0,
                "probe_timeout": 60.0}
    defaults.update(over)
    return type("Args", (), defaults)()


def test_find_live_platform_dead_verdict_cached(monkeypatch, tmp_path):
    """A probe leg answering with a watchdog 'dead' line ends the ladder
    immediately (no probe-retry / probe-auto — the plugin would hang
    identically), records dead_platform in the verdict cache, and the
    NEXT invocation short-circuits with zero probe subprocesses."""
    cache = str(tmp_path / "probe_verdict.json")
    monkeypatch.setattr(bench, "PROBE_CACHE_PATH", cache)
    monkeypatch.setattr(bench, "CACHE_DIR", str(tmp_path))
    dead_line = ({"probe": "dead", "platform": "axon",
                  "reason": "watchdog"}, False, False, False)
    spawn = ScriptedSpawn({"probe": dead_line})
    monkeypatch.setattr(bench, "_spawn", spawn)
    platform, env, recently_dead, dead = bench._find_live_platform(
        _probe_args(tmp_path))
    assert (platform, env, recently_dead, dead) == (None, {}, True, "axon")
    assert spawn.calls == ["probe"]  # no retry, no probe-auto
    with open(cache) as f:
        assert json.load(f)["dead_platform"] == "axon"
    # second invocation: the cached dead-platform verdict short-circuits
    spawn2 = ScriptedSpawn({})
    monkeypatch.setattr(bench, "_spawn", spawn2)
    platform, env, recently_dead, dead = bench._find_live_platform(
        _probe_args(tmp_path))
    assert (platform, recently_dead, dead) == (None, True, "axon")
    assert spawn2.calls == []  # zero probe subprocesses


def test_find_live_platform_live_verdict_unchanged(monkeypatch, tmp_path):
    """A live probe still resolves and caches exactly as before (no
    dead_platform) — the axon fail-fast must not disturb the happy path."""
    cache = str(tmp_path / "probe_verdict.json")
    monkeypatch.setattr(bench, "PROBE_CACHE_PATH", cache)
    monkeypatch.setattr(bench, "CACHE_DIR", str(tmp_path))
    ok_line = ({"probe": "ok", "platform": "tpu",
                "device_kind": "x"}, False, False, False)
    spawn = ScriptedSpawn({"probe": ok_line})
    monkeypatch.setattr(bench, "_spawn", spawn)
    platform, env, recently_dead, dead = bench._find_live_platform(
        _probe_args(tmp_path))
    assert (platform, env, recently_dead, dead) == ("tpu", {}, False, None)
    with open(cache) as f:
        data = json.load(f)
    assert data["platform"] == "tpu" and not data.get("dead_platform")


# xla is the default every other worker test already measures; the pallas
# worker is the row-attribution case that needs its own compile
@pytest.mark.parametrize("engine", [
    pytest.param("xla", marks=pytest.mark.slow), "pallas"])
def test_worker_row_round_trips_kernel_engine(engine, capsys):
    """A real (tiny, CPU) --worker measurement under each tick-kernel
    engine: the JSON row must carry the kernel_engine that actually ran,
    so BENCH_*.json rows attribute wins to the right engine (and the
    pallas run exercises the interpret-mode kernels end-to-end through
    the bench worker)."""
    rc = bench.main(["--worker", "--nodes", "16", "--batch", "2",
                     "--phases", "3", "--snapshots", "2", "--repeats", "1",
                     "--kernel-engine", engine])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert row["metric"] == "node_ticks_per_sec_per_chip"
    assert row["kernel_engine"] == engine
    assert row["value"] > 0


@pytest.mark.slow
def test_stream_worker_row_round_trips_memo_books(capsys):
    """A real (tiny, CPU) --stream --worker A/B under the memo plane: the
    row must carry the memo knob, the dup mix, the coalesce/cache/
    fast-forward books and BOTH throughputs (memoized effective vs the
    memo-off baseline measured in the same process), so a duplicate-heavy
    BENCH row can never pass a memoized number off as raw execution."""
    rc = bench.main(["--worker", "--stream", "--graph", "ring",
                     "--nodes", "8", "--batch", "2", "--jobs", "8",
                     "--snapshots", "2", "--repeats", "1",
                     "--dup-rate", "0.5", "--memo", "full"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert row["metric"] == "stream_jobs_per_sec"
    assert row["memo"] == "full" and row["dup_rate"] == 0.5
    assert row["coalesced_jobs"] > 0
    assert row["cache_hits"] == 0  # no --memo-cache: nothing from file
    assert row["ff_skipped_ticks"] >= 0 and row["shadow_checks"] >= 1
    assert 0.0 < row["memo_hit_rate"] < 1.0
    assert row["effective_jobs_per_sec"] > 0
    assert row["effective_jobs_per_sec_off"] > 0
    assert row["memo_speedup"] == pytest.approx(
        row["effective_jobs_per_sec"] / row["effective_jobs_per_sec_off"],
        rel=1e-2)
    # stream rows carry the same analytic cost model as storm rows
    cm = row["cost_model"]
    assert cm["batch"] == 2 and cm["instance_bytes"] > 0
    assert cm["hbm_bytes_per_tick"] == 2 * cm["instance_bytes"] * 2


@pytest.mark.slow
def test_stream_worker_row_round_trips_prefix_books(capsys):
    """A real (tiny, CPU) --stream --worker A/B/C under memo="prefix": the
    row must carry the fork books (prefix_hits == forked_jobs, a depth
    histogram that sums to the fork count) and BOTH denominators — the
    memo-off baseline and the memo=full exact-match arm — so
    prefix_speedup in a BENCH row always isolates what forking buys over
    the best exact-match plane on the identical prefix-packed pool."""
    rc = bench.main(["--worker", "--stream", "--graph", "ring",
                     "--nodes", "8", "--batch", "2", "--jobs", "8",
                     "--snapshots", "2", "--repeats", "1",
                     "--prefix-overlap", "0.75", "--memo", "prefix"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert row["metric"] == "stream_jobs_per_sec"
    assert row["memo"] == "prefix" and row["prefix_overlap"] == 0.75
    # the books balance: every planned fork was admitted, and the depth
    # histogram accounts for each forked job at a real chain depth
    assert row["forked_jobs"] > 0
    assert row["prefix_hits"] == row["forked_jobs"]
    assert row["fork_depth_mean"] > 0
    hist = row["fork_depth_hist"]
    assert hist and all(int(k) >= 1 for k in hist)
    assert sum(hist.values()) == row["forked_jobs"]
    assert row["prefix_evictions"] >= 0
    # three denominators, one pool: memoized, memo-off, memo=full
    assert row["effective_jobs_per_sec"] > 0
    assert row["effective_jobs_per_sec_off"] > 0
    assert row["effective_jobs_per_sec_full"] > 0
    assert row["prefix_speedup"] == pytest.approx(
        row["effective_jobs_per_sec"] / row["effective_jobs_per_sec_full"],
        rel=1e-2)
    # at dup_rate 0 the exact-match plane coalesces nothing — the fork
    # plane is the only thing separating the two memo arms
    assert row["dup_rate"] == 0.0 and row["coalesced_jobs"] == 0


@pytest.mark.slow
def test_graphshard_worker_row_round_trips_kernel_engine(capsys):
    """The graph-sharded worker row carries kernel_engine too (from
    GraphShardedRunner.summarize), alongside the comm/queue engines."""
    rc = bench.main(["--worker", "--graphshard", "2", "--nodes", "16",
                     "--phases", "3", "--snapshots", "2", "--repeats", "1",
                     "--kernel-engine", "pallas"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert row["mode"] == "graphshard"
    assert row["kernel_engine"] == "pallas"
    assert row["value"] > 0
