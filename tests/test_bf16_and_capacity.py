"""CI coverage for two production-only code paths (round-2 VERDICT items 5/6):

1. The bf16 count-matmul fast path (ops/tick.count_dtype) activates only when
   ``jax.default_backend() == "tpu"`` — and tests/conftest.py pins every test
   to CPU, so until now the one numeric-exactness optimization ran only in
   production. ``SimConfig.count_dtype="bfloat16"`` forces the bf16 constants
   through TickKernel and shard_topology on the CPU mesh, and the gate's
   TPU-side decision is unit-tested via the ``backend`` parameter.

2. ``SimConfig.for_workload`` — the capacity-sizing rule that keeps the
   default bench/storm workloads from firing ERR_QUEUE_OVERFLOW (round 2's
   BENCH zeroed itself because C=16 cannot hold the sf-1024 storm's hub-edge
   backlog, sim.go:82-92 head-of-line blocking + marker bursts).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import recorded_window, DenseTopology
from chandy_lamport_tpu.core.syncsim import SyncOracle
from chandy_lamport_tpu.models.delay import FixedDelay
from chandy_lamport_tpu.models.workloads import (
    scale_free,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, UniformJaxDelay
from chandy_lamport_tpu.ops.tick import BF16_EXACT_COUNT, count_dtype
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.fixtures import TopologySpec


def _star(in_degree: int) -> TopologySpec:
    """``in_degree`` spokes all pointing at one hub — the minimal graph whose
    degree bound sits exactly at the bf16-exactness boundary."""
    width = len(str(in_degree + 1))
    ids = [f"N{str(i + 1).zfill(width)}" for i in range(in_degree + 1)]
    nodes = [(nid, 10) for nid in ids]
    links = [(nid, ids[0]) for nid in ids[1:]]
    return TopologySpec(nodes, links)


def test_gate_decision_by_degree_and_backend():
    at_bound = DenseTopology(_star(BF16_EXACT_COUNT))
    past_bound = DenseTopology(_star(BF16_EXACT_COUNT + 1))
    # the TPU decision, exercised without TPU hardware
    assert count_dtype(at_bound, backend="tpu") == jnp.bfloat16
    assert count_dtype(past_bound, backend="tpu") == jnp.float32
    # CPU always takes the safe path under "auto"
    assert count_dtype(at_bound, backend="cpu") == jnp.float32
    # forcing past the exactness bound is an error, not a silent wrong answer
    with pytest.raises(ValueError, match="not exact"):
        count_dtype(past_bound, override="bfloat16")
    assert count_dtype(past_bound, override="float32") == jnp.float32


def _random_program(rng, topo, phases):
    amounts = np.zeros((phases, topo.e), np.int32)
    floor = topo.tokens0.astype(np.int64).copy()
    for ph in range(phases):
        for e in rng.sample(range(topo.e), k=max(1, topo.e // 3)):
            src = int(topo.edge_src[e])
            if floor[src] >= 2:
                amounts[ph, e] += 1
                floor[src] -= 1
    snap = np.full((phases, 1), -1, np.int32)
    snap[1, 0] = rng.randrange(topo.n)
    snap[3, 0] = rng.randrange(topo.n)
    return amounts, snap


@pytest.mark.parametrize("case,mode,cnt", [
    (0, "segsum", "auto"), (1, "segsum", "auto"),   # big-graph formulation
    (0, "matmul", "bfloat16"), (1, "matmul", "bfloat16"),  # TPU fast path
])
def test_sync_reduce_modes_match_oracle(case, mode, cnt):
    """Both per-node reduction formulations reproduce the sequential oracle
    exactly: "segsum" (integer prefix sums — what the 8k-node ladder config
    compiles to) and "matmul" with forced-bf16 count constants (what the
    TPU bench runs). Small graphs auto-pick matmul/f32, so CI forces both."""
    rng = random.Random(7100 + case)
    spec = scale_free(rng.randrange(5, 12), 2, seed=case, tokens=60)
    topo = DenseTopology(spec)
    delay = rng.randrange(1, 4)
    phases = 8
    amounts, snap = _random_program(rng, topo, phases)

    cfg = SimConfig(queue_capacity=32, max_recorded=64, reduce_mode=mode,
                    count_dtype=cnt)
    runner = BatchedRunner(spec, cfg, FixedJaxDelay(delay), batch=1,
                           scheduler="sync")
    assert runner.kernel._mode == mode
    if mode == "matmul" and cnt == "bfloat16":
        assert runner.kernel._cnt == jnp.bfloat16
    final = jax.device_get(
        runner.run_storm(runner.init_batch(), (amounts, snap)))
    lane = jax.tree_util.tree_map(lambda x: x[0], final)
    assert int(lane.error) == 0

    oracle = SyncOracle(topo, FixedDelay(delay))
    for ph in range(phases):
        oracle.bulk_send([int(a) for a in amounts[ph]])
        nodes = [int(x) for x in snap[ph] if x >= 0]
        if nodes:
            oracle.start_snapshots(nodes)
        oracle.tick()
    oracle.drain_and_flush()

    assert oracle.tokens == [int(t) for t in lane.tokens]
    assert oracle.time == int(lane.time)
    for sid in range(int(lane.next_sid)):
        assert oracle.completed[sid] == int(lane.completed[sid]) == topo.n
        for node in range(topo.n):
            assert oracle.frozen[sid][node] == int(lane.frozen[sid, node])
        for e in range(topo.e):
            want = oracle.recorded[sid].get(e, [])
            got = recorded_window(lane, sid, e)
            assert want == got


def test_forced_bf16_sharded_matches_f32_unsharded():
    """shard_topology's bf16 count constants produce bit-identical state to
    the f32 unsharded kernel (exactness, not approximate agreement)."""
    from jax.sharding import Mesh

    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
    from chandy_lamport_tpu.utils.fixtures import (
        read_events_file,
        read_topology_file,
    )
    from chandy_lamport_tpu.utils.goldens import fixture_path
    from chandy_lamport_tpu.parallel.batch import compile_events

    spec = read_topology_file(fixture_path("8nodes.top"))
    script = read_events_file(fixture_path("8nodes-concurrent-snapshots.events"))
    delay = 2

    ref = BatchedRunner(
        spec, SimConfig(queue_capacity=32, count_dtype="float32"),
        FixedJaxDelay(delay), batch=1, scheduler="sync")
    ref_final = jax.tree_util.tree_map(
        lambda x: np.asarray(x)[0],
        jax.device_get(ref.run(ref.init_batch(),
                               compile_events(ref.topo, script))))

    gs = GraphShardedRunner(
        spec, SimConfig(queue_capacity=32, count_dtype="bfloat16"),
        Mesh(np.array(jax.devices()[:2]), ("graph",)), fixed_delay=delay)
    assert gs._cnt == jnp.bfloat16
    got = gs.gather_dense(gs.run_script(gs.init_state(), script))

    assert int(got.error) == 0 == int(ref_final.error)
    for name in ("time", "tokens", "q_len", "has_local", "frozen", "rem",
                 "recording", "rec_cnt", "min_prot", "log_amt",
                 "rec_start", "rec_end", "completed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(ref_final, name)), err_msg=name)


# ---------------------------------------------------------------------------
# capacity sizing (SimConfig.for_workload)
# ---------------------------------------------------------------------------


def test_for_workload_sizes_the_bench_config():
    cfg = SimConfig.for_workload(snapshots=8)
    # 8 markers + 1x(5+1) delay window + 8 HOL slack = 22 -> rounded to 24,
    # the capacity measured overflow-free at the bench shape (round-2 VERDICT)
    assert cfg.queue_capacity == 24
    assert cfg.max_snapshots == 8
    # split-marker mode: markers live in their own [S, E] planes, so the
    # marker term drops out of the ring sizing (bench sync default; C=16
    # measured overflow-free at the bench shape)
    assert SimConfig.for_workload(
        snapshots=8, split_markers=True).queue_capacity == 16
    # floor and rounding
    assert SimConfig.for_workload(snapshots=1, hol_slack=0).queue_capacity == 16
    assert SimConfig.for_workload(snapshots=16).queue_capacity % 8 == 0
    # an explicit capacity override beats the derived size (the CLI's
    # --queue-capacity path)
    assert SimConfig.for_workload(
        snapshots=8, queue_capacity=48).queue_capacity == 48
    # other overrides pass through
    assert SimConfig.for_workload(
        snapshots=2, record_dtype="int16").record_dtype == "int16"


@pytest.mark.slow  # ~10 s; the forced-bf16 differential keeps capacity derivation tier-1
def test_bench_workload_runs_clean_at_derived_capacity():
    """The bench's own storm (scaled to CPU size) fires no overflow at the
    derived capacity — the regression that zeroed BENCH_r02."""
    spec = scale_free(256, 2, seed=3, tokens=26)
    cfg = SimConfig.for_workload(snapshots=8, max_recorded=16,
                                 record_dtype="int16")
    runner = BatchedRunner(spec, cfg, UniformJaxDelay(seed=17), batch=4,
                           scheduler="sync")
    prog = storm_program(
        runner.topo, phases=16, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, 8, 1, 2,
                                            max_phases=16))
    final = runner.run_storm(runner.init_batch_device(), prog)
    summary = BatchedRunner.summarize(final)
    assert summary["error_bits"] == 0
    assert summary["snapshots_completed"] == summary["snapshots_started"]


def test_init_batch_device_matches_host_init():
    spec = scale_free(16, 2, seed=1, tokens=20)
    runner = BatchedRunner(spec, SimConfig(), UniformJaxDelay(seed=5),
                           batch=3, scheduler="sync")
    host = runner.init_batch()
    dev = jax.device_get(runner.init_batch_device())
    for name in host._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(host, name)), np.asarray(getattr(dev, name)),
            err_msg=name)
