"""tools/chaos_smoke.py in tier-1: the robustness canary must stay green.

One subprocess run of the whole battery — every fault class injected once,
recovery (or quarantine, for the deliberately-unrecoverable scenario)
asserted by the tool itself; this test just demands the verdict and pins
the JSON shape the CI driver consumes. The serve-fleet trio
(``--fleet-only``: worker SIGKILL -> lease takeover with the WAL audit
and solo bit-identity, poison quarantine, shed under pressure) is cheap
enough to stay in tier-1 on its own.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ~72 s on the 1-core CI box — far past the ~30 s tier-1 per-test budget
# (the 870 s wall can no longer absorb it); full passes run the battery
@pytest.mark.slow
def test_chaos_smoke_battery_green():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_smoke.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=240)
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    verdict = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert verdict["ok"] is True
    names = [r["scenario"] for r in verdict["scenarios"]]
    # each fault class injected at least once, both crash outcomes, and
    # the marker-plane classes under the snapshot supervisor (ISSUE 4)
    assert {"msg-faults", "crash-pause", "crash-lossy-recovered",
            "crash-lossy-unrecovered", "marker-drop-retry",
            "marker-dup-storm", "marker-drop-exhausted",
            "trace-under-faults", "prefix-fork-audit",
            "prefix-poison-refused"} <= set(names)
    msg = next(r for r in verdict["scenarios"]
               if r["scenario"] == "msg-faults")
    for cls in ("drops", "dups", "jitters"):
        assert msg["fault_events"][cls] > 0
    for row in verdict["scenarios"]:
        # fleet/prefix rows balance their books in their own currencies
        # (WAL audit, prefix_hits == forked_jobs) and carry no token delta
        assert row.get("conservation_delta", 0) == 0
        assert row["ok"], row
    unrec = next(r for r in verdict["scenarios"]
                 if r["scenario"] == "crash-lossy-unrecovered")
    assert unrec["errors_decoded"] == ["ERR_FAULT_UNRECOVERED"]
    assert unrec["quarantined_lanes"] > 0
    # the drop storm stalled an attempt AND every snapshot completed via
    # supervisor retry
    retry = next(r for r in verdict["scenarios"]
                 if r["scenario"] == "marker-drop-retry")
    assert retry["fault_events"]["marker_drops"] > 0
    assert retry["snapshot_lifecycle"]["retried"] > 0
    assert (retry["snapshot_lifecycle"]["completed"]
            == retry["snapshot_lifecycle"]["initiated"])
    # total marker loss beyond the retry budget fails loudly, on the
    # exhausted lanes only
    exhaust = next(r for r in verdict["scenarios"]
                   if r["scenario"] == "marker-drop-exhausted")
    assert exhaust["errors_decoded"] == ["ERR_SNAPSHOT_TIMEOUT"]
    assert exhaust["snapshot_lifecycle"]["failed"] > 0
    assert exhaust["quarantined_lanes"] > 0
    # the flight recorder captured the supervisor's recovery (ISSUE 7):
    # abort -> retry -> marker re-send visible in a decoded lane timeline
    tr = next(r for r in verdict["scenarios"]
              if r["scenario"] == "trace-under-faults")
    assert tr["trace_events"] > 0 and tr["trace_dropped"] == 0
    assert tr["checks"]["abort_retry_reinit_visible"]
    assert tr["snapshot_lifecycle"]["retried"] > 0


# ~25 s on the 1-core box (one jitted engine per fleet worker + the solo
# identity baseline; the poison/shed scenarios ride the jax-free null
# executor) — inside the tier-1 per-test budget, unlike the battery
def test_chaos_smoke_fleet_scenarios_green():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_smoke.py"),
         "--fleet-only"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    verdict = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert verdict["ok"] is True
    rows = {r["scenario"]: r for r in verdict["scenarios"]}
    assert set(rows) == {"fleet-kill-takeover", "fleet-poison-quarantine",
                         "fleet-shed-pressure"}
    # A: a worker really died mid-flight, its lease was taken over, and
    # the WAL audit balanced — zero lost, zero double-served, every
    # served summary bit-identical to a solo run_stream of that request
    takeover = rows["fleet-kill-takeover"]
    assert takeover["books"]["worker_deaths"] >= 1
    assert takeover["books"]["takeovers"] >= 1
    assert takeover["audit"]["lost"] == 0
    assert takeover["audit"]["double_served"] == 0
    assert takeover["checks"]["bit_identical_to_solo"]
    assert takeover["checks"]["killed_exactly_once"]
    # B: the crash-looping request was quarantined as poison with one
    # decoded provenance entry per burned attempt; the rest still served
    poison = rows["fleet-poison-quarantine"]
    assert list(poison["poisoned"]) == ["1"]
    assert len(poison["poisoned"]["1"]["errors"]) == 2
    assert all("SIGKILL" in e for e in poison["poisoned"]["1"]["errors"])
    assert poison["audit"]["lost"] == 0
    # C: shedding dropped exactly admission.shed_order's predicted
    # victims, and the terminal states still conserve every admit
    shed = rows["fleet-shed-pressure"]
    assert shed["shed"] == shed["predicted"]
    assert shed["audit"]["lost"] == 0
    for row in verdict["scenarios"]:
        assert row["ok"], row


# ~45 s on the 1-core box (prefix step + checkpoint producer compiles
# dominate; the poison drive rides the warm executables; the cold
# differential is the in-engine every-fork shadow audit, so no separate
# oracle compile) — the fork plane's tier-1 canary (ISSUE 20)
def test_chaos_smoke_prefix_scenarios_green():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_smoke.py"),
         "--prefix-only"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    verdict = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert verdict["ok"] is True
    rows = {r["scenario"]: r for r in verdict["scenarios"]}
    assert set(rows) == {"prefix-fork-audit", "prefix-poison-refused"}
    # forks happened under armed faults, every one was shadow-audited
    # cold and byte-matched, and the books balance
    audit = rows["prefix-fork-audit"]
    assert audit["forked_jobs"] > 0
    assert audit["prefix_hits"] == audit["forked_jobs"]
    assert audit["shadow_checks"] >= audit["forked_jobs"]
    assert audit["checks"]["faults_fired"]
    assert audit["checks"]["forks_bit_identical_to_cold"]
    # a tampered checkpoint (valid schema, wrong STATE) is refused by
    # the named error, never served silently
    poison = rows["prefix-poison-refused"]
    assert poison["tampered"] > 0
    assert poison["checks"]["poison_refused_by_name"]
    assert "fork shadow" in poison["error"]
    for row in verdict["scenarios"]:
        assert row["ok"], row
