"""tools/chaos_smoke.py in tier-1: the robustness canary must stay green.

One subprocess run of the whole battery — every fault class injected once,
recovery (or quarantine, for the deliberately-unrecoverable scenario)
asserted by the tool itself; this test just demands the verdict and pins
the JSON shape the CI driver consumes.
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_chaos_smoke_battery_green():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_smoke.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=240)
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    verdict = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert verdict["ok"] is True
    names = [r["scenario"] for r in verdict["scenarios"]]
    # each fault class injected at least once, plus both crash outcomes
    assert {"msg-faults", "crash-pause", "crash-lossy-recovered",
            "crash-lossy-unrecovered"} <= set(names)
    msg = next(r for r in verdict["scenarios"]
               if r["scenario"] == "msg-faults")
    for cls in ("drops", "dups", "jitters"):
        assert msg["fault_events"][cls] > 0
    for row in verdict["scenarios"]:
        assert row["conservation_delta"] == 0
        assert row["ok"], row
    unrec = next(r for r in verdict["scenarios"]
                 if r["scenario"] == "crash-lossy-unrecovered")
    assert unrec["errors_decoded"] == ["ERR_FAULT_UNRECOVERED"]
    assert unrec["quarantined_lanes"] > 0
