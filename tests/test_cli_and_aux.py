"""CLI, checkpoint/resume, and metrics-module tests."""

import io
import json
import sys

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.cli import main
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.models.workloads import ring_topology, storm_program
from chandy_lamport_tpu.ops.delay_jax import UniformJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.checkpoint import load_state, save_state
from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
from chandy_lamport_tpu.utils.goldens import fixture_path
from chandy_lamport_tpu.utils.metrics import (
    conservation_delta,
    progress_counters,
    total_tokens,
)


def _capture(argv):
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        code = main(argv)
    finally:
        sys.stdout = old
    return code, out.getvalue()


def test_cli_run_round_trips_golden(tmp_path):
    code, out = _capture(["run", fixture_path("2nodes.top"),
                          fixture_path("2nodes-message.events")])
    assert code == 0
    # output parses back through the golden reader and matches the golden
    p = tmp_path / "out.snap"
    p.write_text(out)
    got = read_snapshot_file(str(p))
    want = read_snapshot_file(fixture_path("2nodes-message.snap"))
    assert got.id == want.id
    assert got.token_map == want.token_map
    assert got.messages == want.messages


def test_cli_test_parity_backend_passes():
    code, out = _capture(["test", "--backend", "parity"])
    assert code == 0
    assert "7/7 passed" in out


def test_cli_storm_reports_counters(tmp_path):
    ckpt = str(tmp_path / "state.npz")
    code, out = _capture(["storm", "--graph", "ring", "--nodes", "8",
                          "--batch", "4", "--phases", "6", "--snapshots", "2",
                          "--checkpoint", ckpt])
    assert code == 0
    counters = json.loads(out)
    assert counters["error_bits"] == 0
    assert counters["conservation_delta"] == 0
    assert counters["snapshots_completed"] == 2 * 4  # per-lane count summed


def test_checkpoint_round_trip(tmp_path):
    spec = ring_topology(6, tokens=50)
    runner = BatchedRunner(spec, SimConfig(), UniformJaxDelay(3), batch=2,
                           scheduler="sync")
    prog = storm_program(runner.topo, phases=5, amount=1)
    final = runner.run_storm(runner.init_batch(), prog)
    path = str(tmp_path / "ck.npz")
    save_state(path, final, meta={"note": "test"})
    restored, meta = load_state(path, runner.init_batch())
    assert meta["note"] == "test"
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(final)),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_shape_mismatch_rejected(tmp_path):
    spec = ring_topology(6, tokens=50)
    runner = BatchedRunner(spec, SimConfig(), UniformJaxDelay(3), batch=2,
                           scheduler="sync")
    path = str(tmp_path / "ck.npz")
    save_state(path, runner.init_batch())
    other = BatchedRunner(ring_topology(7, tokens=50), SimConfig(),
                          UniformJaxDelay(3), batch=2, scheduler="sync")
    with pytest.raises(ValueError, match="mismatch"):
        load_state(path, other.init_batch())


def test_metrics_conservation_under_jit():
    spec = ring_topology(8, tokens=100)
    cfg = SimConfig()
    runner = BatchedRunner(spec, cfg, UniformJaxDelay(9), batch=4,
                           scheduler="sync")
    prog = storm_program(runner.topo, phases=8, amount=2)
    mid = runner.run_storm(runner.init_batch(), prog, drain=False)
    expected = int(runner.topo.tokens0.sum()) * 4
    # mid-run: tokens are in flight, conservation must still hold exactly
    delta = jax.jit(lambda s: conservation_delta(s, cfg, expected))(mid)
    assert int(delta) == 0
    assert int(total_tokens(mid, cfg)) == expected
    counters = progress_counters(mid, cfg, runner.topo.n)
    assert int(counters["queued_messages"]) > 0  # genuinely mid-flight
