"""The 7 reference golden tests (snapshot_test.go:46-108) through the dense
JAX backend — the gate for SURVEY.md §7.2.4: bit-identical snapshots to the
Go reference via the jitted tick kernel."""

import pytest

from chandy_lamport_tpu.api import run_events_file
from chandy_lamport_tpu.utils.compare import (
    assert_snapshots_equal,
    check_tokens,
    sort_snapshots,
)
from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path


@pytest.mark.parametrize("top,events,snaps", REFERENCE_TESTS,
                         ids=[t[1].removesuffix(".events") for t in REFERENCE_TESTS])
def test_golden_dense(top, events, snaps):
    actual, sim = run_events_file(fixture_path(top), fixture_path(events),
                                  backend="jax")
    assert len(actual) == len(snaps)
    check_tokens(sim.node_tokens(), actual)
    expected = [read_snapshot_file(fixture_path(f)) for f in snaps]
    for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
        assert_snapshots_equal(e, a)
