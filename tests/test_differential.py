"""Cross-backend differential tests: parity (pure-Python oracle) vs dense
(jitted JAX kernel) on randomized strongly-connected topologies and scripts.

This is the §4.4 addition the reference lacks: the reference only has 7
hand-written golden cases; here every semantic rule (R1-R9, core/parity.py)
is exercised on random inputs with the SAME Go-exact delay stream on both
backends, so any divergence is a real kernel bug, not scheduling noise.
Both backends emit messages per destination in src-order arrival order, so
snapshots must match EXACTLY (stronger than the golden comparator's
cross-destination tolerance).
"""

import random

import pytest

from chandy_lamport_tpu.api import run_events
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import PassTokenEvent, SnapshotEvent, TickEvent
from chandy_lamport_tpu.models.delay import FixedDelay, GoExactDelay
from chandy_lamport_tpu.utils.fixtures import TopologySpec
from chandy_lamport_tpu.utils.randgen import (
    random_script,
    random_strongly_connected,
)


@pytest.mark.parametrize("case_seed", [
    # every seed compiles its own random topology (~4-7 s each on the
    # 1-core gate box); seed 0 keeps the parity-vs-dense differential in
    # tier-1, the rest of the battery runs in full passes
    0, *(pytest.param(s, marks=pytest.mark.slow) for s in range(1, 8))])
def test_parity_vs_dense_random(case_seed):
    rng = random.Random(1000 + case_seed)
    topo = random_strongly_connected(rng, rng.randrange(3, 12))
    events = random_script(rng, topo, rng.randrange(10, 40))
    cfg = SimConfig(queue_capacity=64, max_recorded=64)

    p_snaps, p_sim = run_events("parity", topo, events,
                                GoExactDelay(777 + case_seed))
    d_snaps, d_sim = run_events("jax", topo, events,
                                GoExactDelay(777 + case_seed), cfg)

    assert p_sim.node_tokens() == d_sim.node_tokens()
    assert p_sim.total_tokens() == d_sim.total_tokens()
    assert len(p_snaps) == len(d_snaps)
    for ps, ds in zip(p_snaps, d_snaps):
        assert ps.id == ds.id
        assert ps.token_map == ds.token_map
        assert ps.messages == ds.messages  # exact order, not just per-dest


@pytest.mark.parametrize("case_seed", [
    0,
    # seed 0 rides tier-1; the rest of the battery runs in full passes
    # (tier-1 wall-clock budget — each seed is a ~8 s compile+run pair;
    # seed 1 moved out when the memo-plane tests joined the gate)
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(3, marks=pytest.mark.slow)])
def test_cascade_vs_fold_exact_impls(case_seed):
    """The two formulations of the bit-exact tick — the reference-literal
    N-step source fold (ops/tick._tick) and the marker-cascade form
    (ops/tick._cascade_tick) — must agree on everything observable,
    INCLUDING the delay sampler's stream position (draws happen at the
    same fold positions or the whole PRNG-order contract R4 is broken)."""
    from chandy_lamport_tpu.core.dense import DenseSim

    rng = random.Random(4400 + case_seed)
    topo = random_strongly_connected(rng, rng.randrange(3, 10))
    events = random_script(rng, topo, rng.randrange(15, 45))
    cfg = SimConfig(queue_capacity=64, max_recorded=64)

    sims, snaps = [], []
    for impl in ("fold", "cascade"):
        sim = DenseSim(topo, GoExactDelay(31 + case_seed), cfg,
                       exact_impl=impl)
        snaps.append(sim.run_events(events))
        sims.append(sim)
    f_sim, c_sim = sims
    assert f_sim.node_tokens() == c_sim.node_tokens()
    assert snaps[0] == snaps[1]
    # error bits need no extra assert: run_events raises DenseBackendError
    # on any sticky bit (core/dense.py check_errors), so a saturated seed
    # surfaces as a clear capacity error, not a snapshot mismatch. The one
    # C-boundary where the impls legitimately differ is pinned below in
    # test_cascade_fold_capacity_edge.
    # same number of PRNG draws consumed at the same points -> identical
    # final sampler state
    import jax
    import numpy as np

    f_leaves = jax.tree_util.tree_leaves(f_sim._host().delay_state)
    c_leaves = jax.tree_util.tree_leaves(c_sim._host().delay_state)
    assert len(f_leaves) == len(c_leaves)
    for a, b in zip(f_leaves, c_leaves):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # cascade-vs-fold[0] keeps the fold differential in tier-1
def test_cascade_fold_capacity_edge():
    """Pin the ONE boundary where the two exact formulations legitimately
    diverge (ops/tick._cascade_tick docstring, VERDICT r4 #4): a marker
    cascade pushing onto a ring that still holds a not-yet-delivered
    selected head at exactly-full C.

    Construction (FixedDelay(1), C=4): at t=0, N2 sends N1 four tokens
    (edge N2->N1 exactly full, all heads eligible at t=1) and N1 starts a
    snapshot (marker on N1->N2, eligible at t=1). Tick 1's fold scans
    sources in sorted order: N1's marker is delivered to N2 FIRST, whose
    re-broadcast (node.go:154-156 -> 97-109) pushes a marker onto the
    still-full N2->N1 ring — the fold has not yet reached source N2, so
    its selected head is still in the ring and the push overflows. The
    cascade pops every selected head up front (selection is fixed at tick
    start, sim.go:100-102), so the same push fits.

    Assertions: fold flags ERR_QUEUE_OVERFLOW at C; cascade completes
    clean at C and matches the parity oracle (whose queues are unbounded,
    like the reference's, queue.go:6-28 — so the cascade is the faithful
    one); at C+1 fold and cascade are bit-identical and both match parity.
    """
    from chandy_lamport_tpu.core.dense import DenseBackendError

    C = 4
    topo = TopologySpec([("N1", 10), ("N2", 10)],
                        [("N1", "N2"), ("N2", "N1")])
    events = [PassTokenEvent("N2", "N1", 1)] * C
    events += [SnapshotEvent("N1"), TickEvent(1)]

    p_snaps, p_sim = run_events("parity", topo, events, FixedDelay(1))

    # exactly-full C: fold overflows, cascade completes and matches parity
    with pytest.raises(DenseBackendError, match="queue capacity exceeded"):
        run_events("jax", topo, events, FixedDelay(1),
                   SimConfig(queue_capacity=C, max_recorded=16),
                   exact_impl="fold")
    c_snaps, c_sim = run_events("jax", topo, events, FixedDelay(1),
                                SimConfig(queue_capacity=C, max_recorded=16),
                                exact_impl="cascade")
    assert p_sim.node_tokens() == c_sim.node_tokens()
    assert c_snaps == p_snaps

    # one more slot: both impls run clean and bit-identical, matching parity
    results = []
    for impl in ("fold", "cascade"):
        snaps, sim = run_events("jax", topo, events, FixedDelay(1),
                                SimConfig(queue_capacity=C + 1,
                                          max_recorded=16),
                                exact_impl=impl)
        results.append((snaps, sim.node_tokens()))
    assert results[0][1] == results[1][1] == p_sim.node_tokens()
    assert results[0][0] == results[1][0] == p_snaps


def test_multi_source_recording_windows():
    """Force what no golden fixture exercises (SURVEY.md §2.2/§4.3): ONE
    snapshot recording in-flight messages on MULTIPLE channels into one
    node, during concurrent snapshots — asserting the sorted-src flatten
    (the determinization of finalizeSnapshot's map-order iteration,
    reference node.go:188-195).

    Construction (FixedDelay(5) makes it deterministic): a complete
    digraph on {N1..N4}; snapshots start at N1 then N2 at t=0; markers
    reach the other nodes at t=5; meanwhile every node keeps sending
    tokens to N1 and N2, which arrive (delay 5) after the receivers'
    local snapshots exist but before the senders' markers do — so both
    snapshots record on all three inbound channels of their initiator,
    with overlapping windows on the shared edges."""
    from chandy_lamport_tpu.models.delay import FixedDelay

    ids = ["N1", "N2", "N3", "N4"]
    topo = TopologySpec([(n, 100) for n in ids],
                        sorted((a, b) for a in ids for b in ids if a != b))
    events = []
    events.append(SnapshotEvent("N1"))
    events.append(SnapshotEvent("N2"))
    for burst in range(3):
        for src in ids:
            for dst in ("N1", "N2"):
                if src != dst:
                    events.append(PassTokenEvent(src, dst, burst + 1))
        events.append(TickEvent(1))

    p_snaps, p_sim = run_events("parity", topo, events, FixedDelay(5))
    d_snaps, d_sim = run_events("jax", topo, events, FixedDelay(5),
                                SimConfig(queue_capacity=64, max_recorded=64))

    assert p_sim.node_tokens() == d_sim.node_tokens()
    assert len(p_snaps) == len(d_snaps) == 2
    for ps, ds in zip(p_snaps, d_snaps):
        assert ps.token_map == ds.token_map
        assert ps.messages == ds.messages  # exact order == sorted-src flatten
        # the scenario's whole point: >1 channel recorded per snapshot
        dest = "N1" if ps.id == 0 else "N2"
        srcs = {m.src for m in ps.messages if m.dest == dest}
        assert len(srcs) >= 2, f"snapshot {ps.id} recorded only {srcs}"
        # per-destination messages must be grouped by src in sorted order
        # (R9): the flatten emits each source's window contiguously
        seq = [m.src for m in ps.messages if m.dest == dest]
        assert seq == sorted(seq)
