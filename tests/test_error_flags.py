"""Every ERR_* bit of the sticky error bitmask must actually fire.

The bitmask is the framework's sanitizer (core/state.py): it replaces the
reference's log.Fatal paths (node.go:113-116, sim.go:49-54) and the silent
unbounded growth of Go's queues/maps/lists with explicit capacity checks.
Round-1 tests only ever asserted ``error == 0``; these tests drive each
overflow/underflow predicate over the edge on BOTH the dense (single-instance
and batched) and graph-sharded paths, so an off-by-one in any predicate
cannot ship silently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.dense import DenseBackendError, DenseSim
from chandy_lamport_tpu.core.spec import (
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.core.state import (
    ERR_QUEUE_OVERFLOW,
    ERR_RECORD_OVERFLOW,
    ERR_SNAPSHOT_OVERFLOW,
    ERR_TICK_LIMIT,
    ERR_TOKEN_UNDERFLOW,
    ERR_VALUE_OVERFLOW,
    F32_EXACT_LIMIT,
    decode_errors,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner, compile_events
from chandy_lamport_tpu.utils.fixtures import TopologySpec


def _pair(tokens=100):
    """Strongly connected 2-node pair."""
    return TopologySpec([("N1", tokens), ("N2", 0)],
                        [("N1", "N2"), ("N2", "N1")])


def _err(sim: DenseSim) -> int:
    return int(jax.device_get(sim.state.error))


# ---------------------------------------------------------------------------
# dense single-instance kernel
# ---------------------------------------------------------------------------

def test_queue_overflow_fires():
    sim = DenseSim(_pair(), FixedJaxDelay(1), SimConfig(queue_capacity=1))
    sim.process_event(PassTokenEvent("N1", "N2", 1))
    assert _err(sim) == 0  # exactly at capacity: no flag
    sim.process_event(PassTokenEvent("N1", "N2", 1))
    assert _err(sim) & ERR_QUEUE_OVERFLOW


def test_token_underflow_fires():
    sim = DenseSim(_pair(tokens=3), FixedJaxDelay(1), SimConfig())
    sim.process_event(PassTokenEvent("N1", "N2", 3))
    assert _err(sim) == 0  # sending the exact balance is legal
    sim.process_event(PassTokenEvent("N1", "N2", 1))
    assert _err(sim) & ERR_TOKEN_UNDERFLOW


def test_snapshot_overflow_fires():
    sim = DenseSim(_pair(), FixedJaxDelay(1), SimConfig(max_snapshots=1))
    sim.process_event(SnapshotEvent("N1"))
    assert _err(sim) == 0
    sim.process_event(SnapshotEvent("N2"))
    assert _err(sim) & ERR_SNAPSHOT_OVERFLOW


def test_record_overflow_fires():
    """With M=1 and three sends queued ahead of the re-broadcast marker, the
    recording channel N1->N2 must overflow its record buffer."""
    sim = DenseSim(_pair(), FixedJaxDelay(1), SimConfig(max_recorded=1))
    sim.process_event(SnapshotEvent("N2"))
    for _ in range(3):
        sim.process_event(PassTokenEvent("N1", "N2", 1))
    sim.process_event(TickEvent(6))
    assert _err(sim) & ERR_RECORD_OVERFLOW


def test_tick_limit_fires_on_non_strongly_connected_graph():
    """N2 has no outbound link, so the initiator N1 never receives a marker
    back and never finalizes — the reference would hang in its drain loop
    (sim.go:116-117 waits on ALL nodes); the kernel hits the tick budget."""
    spec = TopologySpec([("N1", 10), ("N2", 0)], [("N1", "N2")])
    sim = DenseSim(spec, FixedJaxDelay(1), SimConfig(max_ticks=50))
    with pytest.raises(DenseBackendError, match="max_ticks"):
        sim.run_events([SnapshotEvent("N1"), TickEvent(1)])
    assert _err(sim) & ERR_TICK_LIMIT


def test_merge_key_overflow_fires():
    """Token pushes past merge_key_limit must flag ERR_VALUE_OVERFLOW
    before a marker merge key (tok_pushed * KEYMULT + ord) could wrap
    int32 and silently reorder the FIFO (ops/tick.py merge-key scheme)."""
    from chandy_lamport_tpu.ops.tick import merge_key_limit

    runner = BatchedRunner(_pair(), SimConfig(), FixedJaxDelay(1), batch=1,
                           scheduler="sync")
    state = runner.init_batch()
    limit = merge_key_limit(runner.config.max_snapshots)
    state = state._replace(
        tok_pushed=np.full_like(np.asarray(state.tok_pushed), limit))
    script = compile_events(runner.topo, [
        PassTokenEvent("N1", "N2", 1), TickEvent(1)])
    final = jax.device_get(runner.run(jax.device_put(state), script))
    assert int(np.asarray(final.error)[0]) & ERR_VALUE_OVERFLOW


def test_decode_errors_names_every_bit():
    from chandy_lamport_tpu.core.state import ERR_CONSERVATION

    bits = (ERR_QUEUE_OVERFLOW | ERR_SNAPSHOT_OVERFLOW | ERR_RECORD_OVERFLOW
            | ERR_TOKEN_UNDERFLOW | ERR_TICK_LIMIT | ERR_VALUE_OVERFLOW
            | ERR_CONSERVATION)
    assert len(decode_errors(bits)) == 7


def test_conservation_check_fires_on_corrupted_state():
    """BatchedRunner(check_every=K) evaluates the checkTokens invariant
    (test_common.go:298-328) inside the jitted run: a clean storm stays
    clean, a corrupted balance flags ERR_CONSERVATION on that lane only."""
    from chandy_lamport_tpu.core.state import ERR_CONSERVATION
    from chandy_lamport_tpu.models.workloads import (
        scale_free,
        staggered_snapshots,
        storm_program,
    )

    spec = scale_free(16, 2, seed=5, tokens=30)
    runner = BatchedRunner(spec, SimConfig(), FixedJaxDelay(2), batch=2,
                           scheduler="sync", check_every=2)
    prog = storm_program(
        runner.topo, phases=6, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, 2, 1, 2,
                                            max_phases=6))
    clean = jax.device_get(runner.run_storm(runner.init_batch(), prog))
    assert int(np.asarray(clean.error).sum()) == 0

    bad = runner.init_batch()
    tokens = np.asarray(bad.tokens).copy()
    tokens[1, 0] += 7  # lane 1 conjures tokens from nowhere
    bad = bad._replace(tokens=tokens)
    final = jax.device_get(runner.run_storm(bad, prog))
    errs = np.asarray(final.error)
    assert not errs[0] & ERR_CONSERVATION
    assert errs[1] & ERR_CONSERVATION


# ---------------------------------------------------------------------------
# batched sync scheduler
# ---------------------------------------------------------------------------

def test_value_overflow_fires_on_sync_scheduler():
    """A token amount at the f32-exactness limit must flag, not silently
    violate conservation (ADVICE round 1: f32 incidence matmuls are exact
    only below 2^24)."""
    spec = _pair(tokens=F32_EXACT_LIMIT + 10)
    runner = BatchedRunner(spec, SimConfig(), FixedJaxDelay(1), batch=2,
                           scheduler="sync")
    script = compile_events(runner.topo, [
        PassTokenEvent("N1", "N2", F32_EXACT_LIMIT), TickEvent(2)])
    final = jax.device_get(runner.run(runner.init_batch(), script))
    assert np.all(final.error & ERR_VALUE_OVERFLOW)


def test_value_overflow_absent_below_limit():
    spec = _pair(tokens=F32_EXACT_LIMIT + 10)
    runner = BatchedRunner(spec, SimConfig(), FixedJaxDelay(1), batch=2,
                           scheduler="sync")
    script = compile_events(runner.topo, [
        PassTokenEvent("N1", "N2", F32_EXACT_LIMIT - 1), TickEvent(2)])
    final = jax.device_get(runner.run(runner.init_batch(), script))
    assert int(final.error.sum()) == 0
    assert int(final.tokens[0, 1]) == F32_EXACT_LIMIT - 1  # delivered exactly


def test_batched_error_lanes_reported():
    """Per-lane sticky errors surface in summarize()."""
    spec = _pair(tokens=1)
    runner = BatchedRunner(spec, SimConfig(), FixedJaxDelay(1), batch=4,
                           scheduler="sync")
    script = compile_events(runner.topo, [
        PassTokenEvent("N1", "N2", 5), TickEvent(2)])
    final = runner.run(runner.init_batch(), script)
    assert BatchedRunner.summarize(final)["error_lanes"] == 4


def test_record_dtype_int16_halves_footprint_and_guards():
    """SimConfig.record_dtype='int16' shrinks the per-edge log and flags
    amounts beyond int16 range instead of truncating."""
    from chandy_lamport_tpu.utils.metrics import instance_footprint_bytes

    cfg32, cfg16 = SimConfig(), SimConfig(record_dtype="int16")
    shrink = (instance_footprint_bytes(100, 300, cfg32)
              - instance_footprint_bytes(100, 300, cfg16))
    assert shrink == 2 * 300 * cfg32.max_recorded

    spec = _pair(tokens=100_000)
    runner = BatchedRunner(spec, cfg16, FixedJaxDelay(1), batch=1,
                           scheduler="sync")
    assert runner.init_batch().log_amt.dtype == np.int16
    script = compile_events(runner.topo, [
        SnapshotEvent("N2"),                      # records N1->N2
        PassTokenEvent("N1", "N2", 40_000),       # > int16 max while recording
        TickEvent(6)])
    final = jax.device_get(runner.run(runner.init_batch(), script,
                                      drain=False))
    assert int(final.error[0]) & ERR_VALUE_OVERFLOW


def test_record_dtype_int16_exact_path_matches_goldens():
    """int16 records reproduce a golden case bit-exactly (amounts in the
    fixtures are tiny)."""
    from chandy_lamport_tpu.api import run_events_file
    from chandy_lamport_tpu.utils.compare import assert_snapshots_equal, sort_snapshots
    from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
    from chandy_lamport_tpu.utils.goldens import fixture_path

    snaps, _ = run_events_file(fixture_path("3nodes.top"),
                               fixture_path("3nodes-simple.events"),
                               backend="jax",
                               config=SimConfig(record_dtype="int16"))
    expected = [read_snapshot_file(fixture_path("3nodes-simple.snap"))]
    for e, a in zip(sort_snapshots(expected), sort_snapshots(snaps)):
        assert_snapshots_equal(e, a)


# ---------------------------------------------------------------------------
# graph-sharded path (2 shards on the virtual CPU mesh)
# ---------------------------------------------------------------------------

def _gs(spec, cfg, **kw):
    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
    from chandy_lamport_tpu.parallel.mesh import instance_mesh

    mesh = instance_mesh(2, axis_name="graph")
    return GraphShardedRunner(spec, cfg, mesh, fixed_delay=kw.pop("fixed_delay", 1),
                              **kw)


def _ring4(tokens=100):
    ids = ["N1", "N2", "N3", "N4"]
    return TopologySpec([(i, tokens) for i in ids],
                        [(ids[i], ids[(i + 1) % 4]) for i in range(4)])


def _gs_err(runner, final) -> int:
    return int(jax.device_get(final.error))


def test_graphshard_queue_overflow_fires():
    gs = _gs(_ring4(), SimConfig(queue_capacity=1), fixed_delay=4)
    amounts = np.ones((3, gs.topo.e), np.int32)  # 3 phases of sends, slow net
    snap = np.full((3, 1), -1, np.int32)
    final = gs.run_storm(gs.init_state(), amounts, snap)
    assert _gs_err(gs, final) & ERR_QUEUE_OVERFLOW


def test_graphshard_token_underflow_fires():
    gs = _gs(_ring4(tokens=1), SimConfig())
    amounts = np.full((2, gs.topo.e), 5, np.int32)
    snap = np.full((2, 1), -1, np.int32)
    final = gs.run_storm(gs.init_state(), amounts, snap)
    assert _gs_err(gs, final) & ERR_TOKEN_UNDERFLOW


def test_graphshard_snapshot_overflow_fires():
    gs = _gs(_ring4(), SimConfig(max_snapshots=1))
    amounts = np.zeros((2, gs.topo.e), np.int32)
    snap = np.array([[0], [1]], np.int32)  # two initiations, one slot
    final = gs.run_storm(gs.init_state(), amounts, snap)
    assert _gs_err(gs, final) & ERR_SNAPSHOT_OVERFLOW


def test_graphshard_record_overflow_fires():
    """Marker takes 4 hops around the ring; the recorded channel sees a
    token every phase meanwhile — M=1 must overflow."""
    gs = _gs(_ring4(), SimConfig(max_recorded=1))
    amounts = np.ones((6, gs.topo.e), np.int32)
    snap = np.full((6, 1), -1, np.int32)
    snap[0, 0] = 0
    final = gs.run_storm(gs.init_state(), amounts, snap)
    assert _gs_err(gs, final) & ERR_RECORD_OVERFLOW


def test_graphshard_tick_limit_fires():
    """N4 has an outbound arc but no inbound arc: markers never reach it, the
    snapshot can never complete on all 4 nodes, the drain hits max_ticks."""
    spec = TopologySpec(
        [("N1", 10), ("N2", 10), ("N3", 10), ("N4", 10)],
        [("N1", "N2"), ("N2", "N3"), ("N3", "N1"), ("N4", "N1")])
    gs = _gs(spec, SimConfig(max_ticks=50))
    amounts = np.zeros((1, gs.topo.e), np.int32)
    snap = np.array([[0]], np.int32)
    final = gs.run_storm(gs.init_state(), amounts, snap)
    assert _gs_err(gs, final) & ERR_TICK_LIMIT


def test_graphshard_value_overflow_fires():
    gs = _gs(_ring4(tokens=F32_EXACT_LIMIT + 10), SimConfig())
    amounts = np.zeros((2, gs.topo.e), np.int32)
    amounts[0, 0] = F32_EXACT_LIMIT
    snap = np.full((2, 1), -1, np.int32)
    final = gs.run_storm(gs.init_state(), amounts, snap)
    assert _gs_err(gs, final) & ERR_VALUE_OVERFLOW


def test_graphshard_conservation_check_fires():
    """GraphShardedRunner(check_every=K): a clean sharded storm stays clean;
    corrupting one shard's balances flags the replicated ERR_CONSERVATION
    bit via the in-run psum check."""
    from jax.sharding import Mesh

    from chandy_lamport_tpu.core.state import ERR_CONSERVATION
    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner

    spec = erdos_renyi(16, 2.5, seed=11, tokens=80)
    mesh = Mesh(np.array(jax.devices()[:2]), ("graph",))
    gs = GraphShardedRunner(spec, SimConfig(queue_capacity=16),
                            mesh, fixed_delay=2, check_every=2)
    prog = storm_program(gs.topo, phases=6, amount=1,
                         snapshot_phases=staggered_snapshots(gs.topo, 2))
    clean = jax.device_get(gs.run_storm(gs.init_state(),
                                        np.asarray(prog.amounts),
                                        np.asarray(prog.snap)))
    assert int(clean.error) == 0

    bad = jax.device_get(gs.init_state())
    tokens = np.asarray(bad.tokens).copy()
    tokens[0, 0] += 5  # shard 0 conjures tokens
    bad = bad._replace(tokens=tokens)
    final = jax.device_get(gs.run_storm(bad, np.asarray(prog.amounts),
                                        np.asarray(prog.snap)))
    assert int(final.error) & ERR_CONSERVATION
