"""The deterministic fault adversary (models/faults.py) and lane quarantine.

Four claims, each load-bearing for the robustness PR:

  1. OFF IS FREE AND EXACT — with a zero-rate engine (instrumentation in
     the trace, every mask False) all 7 reference goldens stay bit-identical
     to the uninstrumented kernels, and a batched storm's full final state
     matches faults=None leaf for leaf.
  2. EVERY CLASS FIRES AND THE BOOKS BALANCE — drop/dup/jitter/crash each
     produce nonzero event counts under modest rates, and the skew-adjusted
     conservation delta stays exactly zero (utils/metrics.py): the adversary
     moves tokens, it never leaks them.
  3. RECOVERY — a lossy crash AFTER a completed Chandy-Lamport snapshot
     restores from the snapshot's frozen cut (no error bits); the same crash
     BEFORE any completed snapshot raises ERR_FAULT_UNRECOVERED and the lane
     quarantines (freezes) instead of grinding corrupt state forward.
  4. ISOLATION — a quarantined lane never changes healthy lanes' final
     states: arming the adversary on lane 0 only leaves every other lane
     bit-identical to an all-disarmed run.

Every distinct (rates, scheduler) pair costs a fresh XLA trace, so the
tests share runners and vary only runtime data (fault_key) where the claim
allows — seeds live in the key, not the trace. The deepest differentials
(golden parity x7, per-class storms on both schedulers, the scheduled
recovery-vs-quarantine pair) carry the ``slow`` marker: tier-1 runs under a
hard wall-clock budget and tools/chaos_smoke.py already exercises every
fault class + both crash outcomes there; full passes run everything.
"""

import functools

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.api import run_events_file
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import (
    ERR_FAULT_UNRECOVERED,
    decode_error_bits,
)
from chandy_lamport_tpu.models.faults import JaxFaults
from chandy_lamport_tpu.models.workloads import (
    ring_topology,
    scale_free,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, make_fast_delay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.compare import assert_snapshots_equal, sort_snapshots
from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path
from chandy_lamport_tpu.utils.metrics import conservation_delta

SPEC = scale_free(24, 2, seed=5, tokens=100)
CFG = SimConfig.for_workload(snapshots=4, max_recorded=64)
BATCH = 4


def _storm(faults, scheduler="exact", phases=12, quarantine=None,
           spec=SPEC, cfg=CFG, delay=None, state_patch=None, runner=None):
    if runner is None:
        runner = BatchedRunner(
            spec, cfg, delay or make_fast_delay("hash", 11), batch=BATCH,
            scheduler=scheduler, faults=faults,
            quarantine=(faults is not None) if quarantine is None
            else quarantine)
    prog = storm_program(
        runner.topo, phases=phases, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, 2, 1, 2,
                                            max_phases=phases))
    state = runner.init_batch()
    if state_patch is not None:
        state = state_patch(state)
    return runner, jax.device_get(runner.run_storm(state, prog))


def _leaves_sans_key(state):
    # fault_key differs between armed and disarmed runs by construction;
    # every OTHER leaf must match bit for bit
    return jax.tree_util.tree_leaves(state._replace(fault_key=0))


# ---- claim 1: off is free and exact ------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("top,events,snaps", REFERENCE_TESTS,
                         ids=[t[1].removesuffix(".events")
                              for t in REFERENCE_TESTS])
def test_zero_rate_adversary_keeps_goldens_bit_exact(top, events, snaps):
    actual, sim = run_events_file(fixture_path(top), fixture_path(events),
                                  backend="jax", faults=JaxFaults(7))
    expected = [read_snapshot_file(fixture_path(f)) for f in snaps]
    assert len(actual) == len(expected)
    for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
        assert_snapshots_equal(e, a)


@pytest.mark.slow
@pytest.mark.parametrize("scheduler", ["exact", "sync"])
def test_zero_rate_storm_bit_identical_to_off(scheduler):
    # tier-1's fault sentinels are the quarantine-isolation storm below
    # and the fused-megatick marker differential
    # (tests/test_megatick_fused.py) — the zero-rate≡off claim is the
    # weaker subset and rides in full passes
    _, off = _storm(None, scheduler=scheduler)
    _, zero = _storm(JaxFaults(7), scheduler=scheduler)
    for a, b in zip(_leaves_sans_key(off), _leaves_sans_key(zero)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---- claim 2: every class fires, books balance -------------------------


@pytest.mark.slow
@pytest.mark.parametrize("scheduler", ["exact", "sync"])
@pytest.mark.parametrize("classes,kw", [
    # one trace covers all three message-plane classes; the node-plane
    # crash program is a separate trace (it changes the restart hook)
    (("drops", "dups", "jitters"),
     {"drop_rate": 0.05, "dup_rate": 0.05, "jitter_rate": 0.05}),
    (("crashes",),
     {"crash_rate": 0.3, "crash_mode": "pause", "crash_period": 8,
      "crash_len": 2}),
])
def test_fault_classes_fire_and_conserve(classes, kw, scheduler):
    runner, final = _storm(JaxFaults(3, **kw), scheduler=scheduler)
    summary = BatchedRunner.summarize(final)
    for cls in classes:
        assert summary["fault_events"][cls] > 0, summary["fault_events"]
    expected = int(runner.topo.tokens0.sum()) * BATCH
    assert int(conservation_delta(final, CFG, expected)) == 0
    # pause crashes and drop/dup/jitter are all recoverable in-run: no lane
    # may end poisoned
    assert summary["error_lanes"] == 0, summary["errors_decoded"]


@pytest.mark.slow  # ~14 s; quarantine isolation + the chaos fault classes stay tier-1
def test_fault_program_replays_bit_exactly():
    adversary = JaxFaults(3, drop_rate=0.05, dup_rate=0.05, jitter_rate=0.05)
    runner, a = _storm(adversary)
    _, b = _storm(adversary, runner=runner)        # same trace, same keys
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # a different seed is runtime data (the fault_key ramp), not a new
    # trace: rerun the SAME compiled storm under seed-4 keys
    other = JaxFaults(4, drop_rate=0.05, dup_rate=0.05, jitter_rate=0.05)
    _, c = _storm(adversary, runner=runner, state_patch=lambda s: s._replace(
        fault_key=np.asarray(other.init_batch_state(BATCH))))
    assert (BatchedRunner.summarize(a)["fault_events"]
            != BatchedRunner.summarize(c)["fault_events"])


# ---- claim 3: snapshot-rollback recovery vs quarantine -----------------

RING = ring_topology(8, tokens=100)
RING_CFG = SimConfig.for_workload(snapshots=2, max_recorded=128)


def _ring_storm(faults, phases=60):
    runner = BatchedRunner(RING, RING_CFG, FixedJaxDelay(1), batch=2,
                           scheduler="exact", faults=faults,
                           quarantine=faults is not None)
    prog = storm_program(
        runner.topo, phases=phases, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, 1, 1, 2,
                                            max_phases=phases))
    return runner, jax.device_get(runner.run_storm(runner.init_batch(), prog))


@functools.lru_cache(maxsize=1)
def _healthy_ring():
    return _ring_storm(None)


@pytest.mark.slow
def test_lossy_crash_recovers_from_completed_snapshot():
    # snapshot initiates at phase 1 and (ring of 8, fixed delay 1) completes
    # well before tick 35; the deterministic crash window [35, 37) then
    # kills EVERY node — each must restore from the snapshot's frozen cut
    _, healthy = _healthy_ring()
    runner, final = _ring_storm(JaxFaults(3, crash_rate=1.0,
                                          crash_mode="lossy",
                                          crash_start=35, crash_len=2))
    summary = BatchedRunner.summarize(final)
    assert summary["fault_events"]["crashes"] > 0
    assert summary["error_lanes"] == 0, summary["errors_decoded"]
    assert (summary["snapshots_completed"]
            == BatchedRunner.summarize(healthy)["snapshots_completed"])
    expected = int(runner.topo.tokens0.sum()) * 2
    assert int(conservation_delta(final, RING_CFG, expected)) == 0


@pytest.mark.slow
def test_lossy_crash_without_snapshot_quarantines():
    # the same crash at tick 5 — before any snapshot completes — is
    # genuinely unrecoverable: ERR_FAULT_UNRECOVERED fires and the lane
    # freezes at its poisoning tick instead of running the storm out
    _, healthy = _healthy_ring()
    _, final = _ring_storm(JaxFaults(3, crash_rate=1.0, crash_mode="lossy",
                                     crash_start=5, crash_len=2))
    errs = np.asarray(final.error)
    assert np.all(errs & ERR_FAULT_UNRECOVERED)
    assert decode_error_bits(int(errs[0])) == ["ERR_FAULT_UNRECOVERED"]
    # frozen: the quarantined lanes' clocks stopped at the restart tick,
    # far short of the healthy run's final time
    assert np.all(np.asarray(final.time) < np.asarray(healthy.time))


# ---- claim 4: quarantine isolation -------------------------------------


def test_quarantined_lane_never_touches_healthy_lanes():
    adversary = JaxFaults(3, crash_rate=1.0, crash_mode="lossy",
                          crash_start=5, crash_len=2)

    def arm_lane0_only(state):
        key = np.asarray(state.fault_key).copy()
        key[1:] = 0                      # zero key = disarmed (faults.py)
        return state._replace(fault_key=key)

    def disarm_all(state):
        return state._replace(
            fault_key=np.zeros_like(np.asarray(state.fault_key)))

    runner, mixed = _storm(adversary, quarantine=True,
                           state_patch=arm_lane0_only)
    _, clean = _storm(adversary, runner=runner, state_patch=disarm_all)
    assert int(mixed.error[0]) & ERR_FAULT_UNRECOVERED
    assert not np.any(np.asarray(mixed.error)[1:])
    for a, b in zip(_leaves_sans_key(mixed), _leaves_sans_key(clean)):
        a, b = np.asarray(a), np.asarray(b)
        if a.ndim == 0 or a.shape[0] != BATCH:
            continue                     # per-lane leaves only
        np.testing.assert_array_equal(a[1:], b[1:])


# ---- construction-time contracts ---------------------------------------


def test_fold_refuses_fault_engine():
    with pytest.raises(ValueError, match="fold"):
        BatchedRunner(SPEC, CFG, make_fast_delay("hash", 11), batch=2,
                      scheduler="exact", exact_impl="fold",
                      faults=JaxFaults(7))


def test_parity_backend_refuses_fault_engine():
    with pytest.raises(ValueError, match="parity"):
        run_events_file(fixture_path("2nodes.top"),
                        fixture_path("2nodes-message.events"),
                        backend="parity", faults=JaxFaults(7))


@pytest.mark.parametrize("kw", [
    {"drop_rate": -0.1}, {"dup_rate": 1.5},
    {"crash_mode": "explode"},
    {"crash_len": 0}, {"crash_len": 32, "crash_period": 32},
])
def test_adversary_rejects_bad_programs(kw):
    with pytest.raises(ValueError):
        JaxFaults(7, **kw)
