"""Unit tests for the fixture parsers (reference test_common.go:29-193)."""

import pytest

from chandy_lamport_tpu.core.spec import PassTokenEvent, SnapshotEvent, TickEvent
from chandy_lamport_tpu.utils.fixtures import (
    read_events_file,
    read_snapshot_file,
    read_topology_file,
)
from chandy_lamport_tpu.utils.goldens import fixture_path


def test_topology_2nodes():
    t = read_topology_file(fixture_path("2nodes.top"))
    assert t.nodes == [("N1", 1), ("N2", 0)]
    assert t.links == [("N1", "N2"), ("N2", "N1")]


def test_topology_8nodes_comments_ignored():
    t = read_topology_file(fixture_path("8nodes.top"))
    assert len(t.nodes) == 8
    # two bridged bidirectional 4-cycles -> 2*4*2 + 2 arcs
    assert len(t.links) == 18


def test_events_parsing():
    ev = read_events_file(fixture_path("2nodes-message.events"))
    assert ev == [PassTokenEvent("N1", "N2", 1), SnapshotEvent("N2"), TickEvent(1)]


def test_events_tick_default_and_count():
    ev = read_events_file(fixture_path("8nodes-sequential-snapshots.events"))
    ticks = [e.n for e in ev if isinstance(e, TickEvent)]
    assert 10 in ticks  # "tick 10" lines parse their count


def test_events_comments_supported(tmp_path):
    # The reference's comment filter is inert (swapped HasPrefix args,
    # test_common.go:90); ours must actually work.
    p = tmp_path / "c.events"
    p.write_text("# a comment\nsend N1 N2 3\n")
    assert read_events_file(str(p)) == [PassTokenEvent("N1", "N2", 3)]


def test_snapshot_parsing():
    s = read_snapshot_file(fixture_path("2nodes-message.snap"))
    assert s.id == 0
    assert s.token_map == {"N1": 0, "N2": 0}
    assert len(s.messages) == 1
    m = s.messages[0]
    assert (m.src, m.dest, m.message.is_marker, m.message.data) == ("N1", "N2", False, 1)


def test_snapshot_rejects_unknown_message(tmp_path):
    p = tmp_path / "bad.snap"
    p.write_text("0\nN1 5\nN1 N2 marker(0)\n")
    with pytest.raises(ValueError):
        read_snapshot_file(str(p))
