"""clsim-serve-ha (serving/fleet.py): supervisor logic, the worker serve
loop, and the fleet differential.

Tier-1 keeps to the cheap arms: pure host logic (shed ordering, exit
provenance, recipes, the burst/crash-schedule workload builders), the
worker loop driven IN-PROCESS against the shared session runner (one
compile, no spawn), and one real one-worker null-executor fleet (the
spawn plumbing, ~2 s). The multi-worker real-engine differential with
chaos kills rides tools/chaos_smoke.py --fleet-only
(tests/test_chaos_smoke.py) and the full multiprocess scaling pass here
is the slow marker.
"""

import os

import pytest

from chandy_lamport_tpu.core.spec import (
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.models.workloads import (
    ServeRequest,
    burst_workload,
    crash_schedule,
    ring_topology,
    serve_workload,
)
from chandy_lamport_tpu.serving.admission import shed_order
from chandy_lamport_tpu.serving.fleet import (
    _exit_provenance,
    fleet_run,
    recipe_runner,
    worker_serve,
)
from chandy_lamport_tpu.serving.spool import AdmissionSpool


def _req(job, arrival=0, tenant=0, priority=1, slack=32, tokens=2):
    return ServeRequest(
        job=job, arrival_step=arrival, tenant=tenant, priority=priority,
        deadline_step=arrival + slack,
        events=[PassTokenEvent(src="N1", dest="N2", tokens=tokens),
                SnapshotEvent(node_id="N3"), TickEvent(4)])


class TestHostLogic:
    def test_shed_order_drops_least_urgent_first(self):
        reqs = [
            ServeRequest(0, 0, 0, 1, 100, []),   # high class
            ServeRequest(1, 0, 0, 0, 50, []),    # low class, tight
            ServeRequest(2, 0, 0, 0, 90, []),    # low class, slack
            ServeRequest(3, 5, 0, 0, 90, []),    # ... later arrival
        ]
        order = [r.job for r in shed_order(reqs)]
        # lowest priority first; within it the latest deadline (most
        # slack) first, then the latest arrival; high class dies last
        assert order == [3, 2, 1, 0]

    def test_shed_order_mirrors_edf_admission(self):
        reqs = serve_workload(ring_topology(4), 8, seed=5, priorities=3)
        shed = [r.job for r in shed_order(reqs)]
        from chandy_lamport_tpu.serving.admission import order_eligible
        admit = [r.job for r in order_eligible(reqs, "edf")]
        # the job shed FIRST is never the one EDF would admit first
        assert shed[0] != admit[0]
        assert sorted(shed) == sorted(admit)

    def test_recipe_runner_null_forms(self):
        assert recipe_runner(None) is None
        assert recipe_runner({}) is None
        assert recipe_runner({"kind": "null"}) is None
        with pytest.raises(ValueError, match="unknown worker recipe"):
            recipe_runner({"kind": "warp-drive"})

    def test_exit_provenance_decodes_signals(self):
        import signal

        assert "SIGKILL" in _exit_provenance(-int(signal.SIGKILL))
        assert _exit_provenance(0) == "exited with code 0"
        assert _exit_provenance(None) == "still running"
        assert "signal 250" in _exit_provenance(-250)

    def test_burst_workload_keeps_clock_and_slack(self):
        spec = ring_topology(4)
        base = serve_workload(spec, 12, seed=7, rate=1.0)
        burst = burst_workload(spec, 12, seed=7, rate=1.0,
                               burst_period=16, burst_factor=8.0)
        arrivals = [r.arrival_step for r in burst]
        assert arrivals == sorted(arrivals)      # monotone clock
        for b, o in zip(burst, base):
            # re-timing preserves payload and the deadline SLACK
            assert b.events == o.events
            assert (b.deadline_step - b.arrival_step
                    == o.deadline_step - o.arrival_step)
        # bursts actually compress: some inter-arrival gap in the burst
        # half beats the uniform trace's mean gap
        gaps = [b - a for a, b in zip(arrivals, arrivals[1:])]
        assert min(gaps) == 0 or min(gaps) < max(gaps)

    def test_crash_schedule(self):
        assert crash_schedule(3, 2.0, start_s=1.0) == [1.0, 3.0, 5.0]
        assert crash_schedule(0, 2.0) == []


class TestWorkerLoop:
    def test_null_worker_serves_everything(self, tmp_path):
        spool = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        for j in range(5):
            spool.admit(_req(j, arrival=j))
        books = worker_serve("w0", spool, None, lease_limit=2,
                             max_wall_s=30)
        assert books["served"] == 5 and books["leased"] == 5
        assert books["late_rejected"] == 0
        assert spool.finished()
        assert spool.results()[0]["served_from"] == "null"

    def test_reclaimed_lease_result_is_discarded(self, tmp_path):
        spool = AdmissionSpool(str(tmp_path / "wal.jsonl"), lease_ttl=5.0)
        spool.admit(_req(0))
        # simulate the stalled worker: its lease is reclaimed and the
        # job redelivered to (and completed by) the takeover before the
        # original's commit arrives
        spool.lease("w-slow", limit=1, now=0.0)
        spool.reclaim_expired(now=10.0)
        spool.lease("w-takeover", limit=1, now=11.0)
        assert spool.complete(0, "w-takeover", {"t": 1}, now=12.0)
        assert spool.complete(0, "w-slow", {"t": 1}, now=13.0) is False
        assert spool.done_by[0] == "w-takeover"

    def test_inprocess_worker_bit_identical_to_solo(
            self, tmp_path, ring8_sync_stream_runner):
        # the tier-1 identity sentinel: the worker loop in THIS process
        # against the shared session runner — every served summary must
        # equal a solo singleton run_stream of the same request (the
        # multiprocess version of this proof lives in chaos_smoke's
        # fleet-kill-takeover scenario)
        runner = ring8_sync_stream_runner
        reqs = [_req(j, arrival=j, tokens=j + 1) for j in range(3)]
        spool = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        for r in reqs:
            spool.admit(r)
        books = worker_serve("w0", spool, runner, lease_limit=2,
                             max_wall_s=60)
        assert books["served"] == 3
        assert spool.finished()
        for j, row in spool.results().items():
            pool = runner.pack_jobs([reqs[j].events], content_keys=True)
            _, stream = runner.run_stream(pool, stretch=2, drain_chunk=8)
            (solo,) = runner.stream_results(stream)
            solo = {k: v for k, v in solo.items()
                    if k not in ("job", "admit_step")}
            got = {k: v for k, v in row.items()
                   if k not in ("digest", "served_from")}
            assert got == solo, j
            assert row["served_from"] == "fleet-exec"

    def test_duplicate_content_served_from_shared_cache(
            self, tmp_path, ring8_sync_stream_runner):
        import copy

        # a second worker handle sharing the memo file must answer a
        # digest the first already served from the cache, no lane burned
        runner = copy.copy(ring8_sync_stream_runner)
        runner.memo_cache_path = str(tmp_path / "memo.jsonl")
        reqs = [_req(0, tokens=7), _req(1, tokens=7)]   # same content
        spool = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        spool.admit(reqs[0])
        b0 = worker_serve("w0", spool, runner, lease_limit=1,
                          max_wall_s=60)
        spool.admit(reqs[1])
        b1 = worker_serve("w1", spool, runner, lease_limit=1,
                          max_wall_s=60)
        assert b0["cache_served"] == 0 and b1["cache_served"] == 1
        res = spool.results()
        assert res[0]["served_from"] == "fleet-exec"
        assert res[1]["served_from"] == "fleet-cache"
        a = {k: v for k, v in res[0].items() if k != "served_from"}
        b = {k: v for k, v in res[1].items() if k != "served_from"}
        assert a == b                       # identical bytes, same digest


class TestFleetRun:
    def test_one_null_worker_fleet(self, tmp_path):
        # the real spawn plumbing once in tier-1: one process, null
        # executor, everything served, books and audit conserved
        reqs = [_req(j, arrival=j) for j in range(4)]
        rep = fleet_run(reqs, spool_path=str(tmp_path / "wal.jsonl"),
                        workers=1, recipe=None, lease_ttl=5.0,
                        max_wall_s=60)
        assert rep["served"] == 4 and rep["goodput"] == 1.0
        assert rep["audit"]["lost"] == 0
        assert rep["audit"]["double_served"] == 0
        assert rep["books"]["worker_deaths"] == 0
        assert not rep["timed_out"]
        assert rep["serve_schema"] >= 1
        assert rep["lat_p50_s"] is not None

    def test_rejects_zero_workers(self, tmp_path):
        with pytest.raises(ValueError, match="workers"):
            fleet_run([], spool_path=str(tmp_path / "w.jsonl"), workers=0)

    def test_shed_happens_before_spawn(self, tmp_path):
        # admission-time pressure control: victims are exactly
        # shed_order's prediction, decided before any worker races
        reqs = serve_workload(ring_topology(4), 8, seed=3, rate=4.0,
                              priorities=3)
        rep = fleet_run(reqs, spool_path=str(tmp_path / "wal.jsonl"),
                        workers=1, recipe=None, shed_backlog=3,
                        max_wall_s=60)
        victims = sorted(r.job for r in shed_order(reqs)[:5])
        assert sorted(int(j) for j in rep["shed"]) == victims
        assert rep["served"] == 3
        assert rep["books"]["shed"] == 5

    @pytest.mark.slow
    def test_multiworker_fleet_with_injected_crash(self, tmp_path):
        # the full differential: two REAL engine workers, one injected
        # SIGKILL from the supervisor's crash schedule, bit-identity and
        # conservation at the end (the scheduled cousin of chaos_smoke's
        # deterministic kill-on-lease scenario)
        spec = ring_topology(8, tokens=16)
        reqs = serve_workload(spec, 6, seed=13, rate=2.0, tenants=2,
                              priorities=3, max_phases=4,
                              deadline_slack=(8, 64))
        recipe = {"kind": "ring-stream", "n": 8, "tokens": 16,
                  "snapshots": 2, "max_recorded": 32, "batch": 2,
                  "scheduler": "sync",
                  "memo_cache": str(tmp_path / "memo.jsonl")}
        rep = fleet_run(reqs, spool_path=str(tmp_path / "wal.jsonl"),
                        workers=2, recipe=recipe, lease_ttl=4.0,
                        crash_schedule=crash_schedule(1, 1.0, start_s=4.0),
                        restart_backoff=0.2, max_wall_s=180)
        assert rep["served"] == 6
        assert rep["books"]["injected_kills"] == 1
        assert rep["books"]["worker_deaths"] >= 1
        assert rep["audit"]["lost"] == 0
        assert rep["audit"]["double_served"] == 0
        solo = recipe_runner({**recipe, "memo_cache": None})
        for j, row in rep["results"].items():
            pool = solo.pack_jobs([reqs[int(j)].events],
                                  content_keys=True)
            _, stream = solo.run_stream(pool, stretch=2, drain_chunk=8)
            (srow,) = solo.stream_results(stream)
            srow = {k: v for k, v in srow.items()
                    if k not in ("job", "admit_step")}
            got = {k: v for k, v in row.items()
                   if k not in ("digest", "served_from")}
            assert got == srow, j
