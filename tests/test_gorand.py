"""Unit tests for the Go ``math/rand`` reimplementation (ops/gorand.py).

The end-to-end proof of bit-exactness is the golden suite
(test_parity_golden.py); these tests pin down the individual pieces so a
regression localizes.
"""

import numpy as np
import pytest

from chandy_lamport_tpu.config import REFERENCE_TEST_SEED
from chandy_lamport_tpu.ops.gorand import GoRand, load_cooked_table, seedrand


def test_seedrand_lehmer_chain():
    # x' = 48271 * x mod (2^31 - 1), checked against direct modular arithmetic.
    x = 1
    for _ in range(100):
        nxt = seedrand(x)
        assert nxt == (48271 * x) % ((1 << 31) - 1)
        x = nxt
    assert x == pow(48271, 100, (1 << 31) - 1)


def test_cooked_table_shape_and_dtype():
    t = load_cooked_table()
    assert len(t) == 607
    assert all(0 <= v < (1 << 64) for v in t)


def test_zero_seed_becomes_sentinel():
    # Go: seed 0 (and multiples of 2^31-1) remap to 89482311 (rng.go Seed).
    a = GoRand(0)
    b = GoRand((1 << 31) - 1)
    assert [a.intn(1000) for _ in range(20)] == [b.intn(1000) for _ in range(20)]


def test_negative_seed_reduction():
    # Go adds M after truncated mod; for seed = -5: -5 % M + M == M - 5.
    a = GoRand(-5)
    b = GoRand(((1 << 31) - 1) - 5)
    assert [a.intn(1000) for _ in range(20)] == [b.intn(1000) for _ in range(20)]


def test_int63_int31_relationship():
    a = GoRand(12345)
    b = GoRand(12345)
    for _ in range(50):
        assert b.int31() == a.int63() >> 32


def test_int31n_power_of_two_masks():
    a = GoRand(7)
    b = GoRand(7)
    for _ in range(50):
        assert b.int31n(8) == a.int31() & 7


def test_intn_range_and_determinism():
    rng = GoRand(REFERENCE_TEST_SEED + 1)
    draws = [rng.intn(5) for _ in range(1000)]
    assert set(draws) <= {0, 1, 2, 3, 4}
    rng2 = GoRand(REFERENCE_TEST_SEED + 1)
    assert draws == [rng2.intn(5) for _ in range(1000)]
    # Regression pin: first draws of the reference test stream (validated
    # end-to-end against the 21 golden fixtures).
    assert draws[:10] == [3, 2, 3, 2, 0, 1, 2, 1, 0, 1]
    assert GoRand(REFERENCE_TEST_SEED + 1).uint64() == 13890532773879204894


def test_intn_rejects_bad_args():
    rng = GoRand(1)
    with pytest.raises(ValueError):
        rng.intn(0)
    with pytest.raises(ValueError):
        rng.int31n(-3)


def test_state_arrays_export():
    rng = GoRand(99)
    vec, tap, feed = rng.state_arrays()
    assert vec.shape == (607,) and vec.dtype == np.uint64
    assert 0 <= tap < 607 and 0 <= feed < 607
