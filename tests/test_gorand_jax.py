"""ops/gorand_jax must advance the exact same stream as the host GoRand.

Each test draws the whole stream in one jitted ``lax.scan`` (a single
dispatch) and compares against the host generator's python-int stream.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from chandy_lamport_tpu.config import REFERENCE_TEST_SEED
from chandy_lamport_tpu.ops import gorand_jax
from chandy_lamport_tpu.ops.gorand import GoRand


def _jax_state(seed):
    vec, tap, feed = GoRand(seed).state_arrays()
    return (jnp.asarray(vec, jnp.uint64), jnp.int32(tap), jnp.int32(feed))


def _stream(draw_fn, state, n):
    def step(s, _):
        v, s = draw_fn(s)
        return s, v

    state, vals = jax.jit(lambda s: lax.scan(step, s, None, length=n))(state)
    return np.asarray(vals), state


@pytest.mark.parametrize("seed", [1, 42, REFERENCE_TEST_SEED + 1])
def test_uint64_stream_matches_host(seed):
    host = GoRand(seed)
    vals, _ = _stream(gorand_jax.uint64, _jax_state(seed), 2000)
    expect = np.array([host.uint64() for _ in range(2000)], dtype=np.uint64)
    np.testing.assert_array_equal(vals, expect)


@pytest.mark.parametrize("n", [5, 7, 8, 100])
def test_intn_matches_host(n):
    seed = REFERENCE_TEST_SEED + 1
    host = GoRand(seed)
    vals, _ = _stream(lambda s: gorand_jax.intn(s, n), _jax_state(seed), 1000)
    expect = np.array([host.intn(n) for _ in range(1000)], dtype=np.int32)
    np.testing.assert_array_equal(vals, expect)


def test_intn_rejection_loop_is_stream_safe():
    """Exercise the rejection while_loop: for n = 2^30 + 1,
    2^31 % n = 2^30 - 1, so ~25% of int31 draws reject and redraw. The
    stream must stay aligned with the host through every rejection."""
    n = (1 << 30) + 1
    seed = 12345
    host = GoRand(seed)
    vals, state = _stream(lambda s: gorand_jax.intn(s, n), _jax_state(seed), 500)
    expect = np.array([host.intn(n) for _ in range(500)], dtype=np.int32)
    np.testing.assert_array_equal(vals, expect)
    x, _ = gorand_jax.uint64(state)
    assert int(x) == host.uint64()
