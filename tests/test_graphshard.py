"""Graph-sharded runner: bit-equality with the unsharded sync kernel on the
virtual 8-device CPU mesh, plus invariants under the per-shard uniform
stream. The equality test is strong: every queue slot, recording flag and
frozen balance must match the single-device result after reassembling the
shard-partitioned edge order."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from chandy_lamport_tpu.config import SimConfig
from types import SimpleNamespace

from chandy_lamport_tpu.core.state import recorded_window
from chandy_lamport_tpu.models.workloads import (
    erdos_renyi,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
from chandy_lamport_tpu.utils.metrics import progress_counters


def _graph_mesh(p):
    devs = jax.devices()[:p]
    return Mesh(np.array(devs), ("graph",))


def _edge_permutation(gs_runner):
    """Global edge index -> (shard, local slot) flattened order."""
    topo = gs_runner.topo
    shard_of = topo.edge_src // gs_runner.nl
    perm = []
    for p in range(gs_runner.shards):
        perm.extend([i for i in range(topo.e) if shard_of[i] == p])
    return perm


@pytest.mark.parametrize("shards", [2, 4])
def test_sharded_matches_unsharded_fixed_delay(shards):
    spec = erdos_renyi(16, 2.5, seed=11, tokens=80)
    cfg = SimConfig(queue_capacity=16, max_snapshots=8, max_recorded=16)
    delay = 2
    phases, n_snaps = 10, 3

    # unsharded reference result (sync scheduler, one lane)
    ref = BatchedRunner(spec, cfg, FixedJaxDelay(delay), batch=1,
                        scheduler="sync")
    prog = storm_program(ref.topo, phases=phases, amount=1,
                         snapshot_phases=staggered_snapshots(ref.topo, n_snaps))
    ref_final = jax.tree_util.tree_map(
        lambda x: np.asarray(x)[0],
        jax.device_get(ref.run_storm(ref.init_batch(), prog)))
    assert int(ref_final.error) == 0

    # sharded run
    gs = GraphShardedRunner(spec, cfg, _graph_mesh(shards),
                            fixed_delay=delay)
    final = jax.device_get(gs.run_storm(gs.init_state(),
                                        np.asarray(prog.amounts),
                                        np.asarray(prog.snap)))
    assert int(final.error) == 0
    assert int(final.time) == int(ref_final.time)
    assert int(final.next_sid) == n_snaps

    # node state: concatenate shard blocks
    np.testing.assert_array_equal(final.tokens.reshape(-1), ref_final.tokens)
    n = gs.topo.n
    for name in ("has_local", "frozen", "rem", "done_local"):
        got = np.concatenate(
            [getattr(final, name)[p] for p in range(shards)], axis=-1)
        np.testing.assert_array_equal(got, getattr(ref_final, name),
                                      err_msg=name)
    np.testing.assert_array_equal(final.completed, ref_final.completed)

    # edge state: map shard-local slots back to global edge order
    perm = _edge_permutation(gs)
    counts = [sum(1 for i in range(gs.topo.e)
                  if gs.topo.edge_src[i] // gs.nl == p)
              for p in range(shards)]
    # split representation: rings never hold markers, so no packed q_meta
    # slot ever carries the marker bit (core/state.py "Packed ring slots")
    assert not (np.asarray(ref_final.q_meta) & 1).any()
    for name in ("q_data", "q_meta", "q_head", "q_len",
                 "tok_pushed", "mk_cnt"):
        parts = [getattr(final, name)[p][:counts[p]] for p in range(shards)]
        got = np.concatenate(parts, axis=0)
        want = getattr(ref_final, name)[perm]
        np.testing.assert_array_equal(got, want, err_msg=name)
    for name in ("recording", "rec_start", "rec_end",
                 "m_pending", "m_rtime", "m_key"):
        parts = [getattr(final, name)[p][:, :counts[p]] for p in range(shards)]
        got = np.concatenate(parts, axis=1)
        want = getattr(ref_final, name)[:, perm]
        np.testing.assert_array_equal(got, want, err_msg=name)
    for name in ("rec_cnt", "min_prot"):
        parts = [getattr(final, name)[p][:counts[p]] for p in range(shards)]
        got = np.concatenate(parts, axis=0)
        np.testing.assert_array_equal(got, getattr(ref_final, name)[perm],
                                      err_msg=name)
    # the shared per-edge log: [L, Em] per shard
    parts = [final.log_amt[p][:, :counts[p]] for p in range(shards)]
    got = np.concatenate(parts, axis=1)
    np.testing.assert_array_equal(got, ref_final.log_amt[:, perm],
                                  err_msg="log_amt")


def test_sharded_uniform_stream_invariants():
    """Independent per-shard streams: conservation + completion still hold."""
    spec = erdos_renyi(24, 3.0, seed=4, tokens=100)
    cfg = SimConfig(queue_capacity=16, max_snapshots=8, max_recorded=32)
    gs = GraphShardedRunner(spec, cfg, _graph_mesh(4), seed=77)
    prog = storm_program(gs.topo, phases=20, amount=1,
                         snapshot_phases=staggered_snapshots(gs.topo, 5))
    final = jax.device_get(gs.run_storm(gs.init_state(),
                                        np.asarray(prog.amounts),
                                        np.asarray(prog.snap)))
    assert int(final.error) == 0
    assert int(final.q_len.sum()) == 0
    assert int(final.tokens.sum()) == int(gs.topo.tokens0.sum())
    for sid in range(5):
        assert int(final.completed[sid]) == gs.topo.n
        frozen = int(np.concatenate(
            [final.frozen[p][sid] for p in range(4)]).sum())
        recorded = 0
        for p in range(4):
            # per-shard view with just the window-decode fields (the
            # replicated scalars in ShardedState are 0-d, so a full
            # tree_map slice would fail)
            shard = SimpleNamespace(
                log_amt=final.log_amt[p], rec_cnt=final.rec_cnt[p],
                rec_start=final.rec_start[p], rec_end=final.rec_end[p],
                recording=final.recording[p])
            for e in range(shard.rec_start.shape[-1]):
                recorded += sum(recorded_window(shard, sid, e))
        assert frozen + recorded == int(gs.topo.tokens0.sum())
