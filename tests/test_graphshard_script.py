"""Event scripts on the graph-sharded runner.

Round-1 gap (VERDICT): GraphShardedRunner only ran storm programs, so the
TP-analogue axis was validated on synthetic traffic only. These tests run the
REFERENCE event scripts (semantics root test_common.go:79-140) sharded over
the virtual CPU mesh with a fixed delay stream and demand bit-equality with
the unsharded sync backend after gather_dense() reassembly — every queue
slot, recording flag, frozen balance and recorded message.

Also covers ShardedState checkpoint round-trips (round-1 gap: checkpointing
was typed/tested only for DenseState).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import decode_snapshot
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner, compile_events
from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
from chandy_lamport_tpu.utils.fixtures import read_events_file, read_topology_file
from chandy_lamport_tpu.utils.goldens import fixture_path


def _graph_mesh(p):
    return Mesh(np.array(jax.devices()[:p]), ("graph",))


def _lane0(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[0], tree)


CASES = [
    # the two smallest goldens ride outside the tier-1 wall: the
    # concurrent-snapshot 4-shard leg and the largest fixture keep the
    # sharded-vs-unsharded script differential in tier-1
    pytest.param("2nodes.top", "2nodes-message.events", 2,
                 marks=pytest.mark.slow),
    pytest.param("8nodes.top", "8nodes-sequential-snapshots.events", 2,
                 marks=pytest.mark.slow),
    ("8nodes.top", "8nodes-concurrent-snapshots.events", 4),
    ("10nodes.top", "10nodes.events", 2),
]


@pytest.mark.parametrize("top,events,shards", CASES)
def test_script_sharded_matches_unsharded(top, events, shards):
    spec = read_topology_file(fixture_path(top))
    script = read_events_file(fixture_path(events))
    cfg = SimConfig(queue_capacity=32, max_snapshots=16, max_recorded=32)
    delay = 2

    ref = BatchedRunner(spec, cfg, FixedJaxDelay(delay), batch=1,
                        scheduler="sync")
    ref_final = _lane0(jax.device_get(
        ref.run(ref.init_batch(), compile_events(ref.topo, script))))
    assert int(ref_final.error) == 0

    gs = GraphShardedRunner(spec, cfg, _graph_mesh(shards), fixed_delay=delay)
    got = gs.gather_dense(gs.run_script(gs.init_state(), script))

    assert int(got.error) == 0
    for name in ("time", "tokens", "q_meta", "q_data", "q_head",
                 "q_len", "tok_pushed", "mk_cnt", "m_pending", "m_rtime",
                 "m_key", "next_sid", "started", "has_local", "frozen", "rem",
                 "done_local", "recording", "rec_cnt", "min_prot",
                 "log_amt", "rec_start", "rec_end", "completed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(ref_final, name)), err_msg=name)

    # decoded snapshots agree too (the user-facing artifact)
    for sid in range(int(got.next_sid)):
        a = decode_snapshot(gs.topo, got, sid)
        b = decode_snapshot(ref.topo, ref_final, sid)
        assert a.token_map == b.token_map
        assert a.messages == b.messages


def test_script_trailing_events_no_tick():
    """A script ending in a send (no trailing tick) must leave the message
    queued but undelivered — same contract as the dense no-drain path."""
    from chandy_lamport_tpu.core.spec import PassTokenEvent, TickEvent

    spec = read_topology_file(fixture_path("2nodes.top"))
    gs = GraphShardedRunner(spec, SimConfig(), _graph_mesh(2), fixed_delay=1)
    script = gs.compile_script(
        [TickEvent(1), PassTokenEvent("N1", "N2", 1)])
    assert np.asarray(script.do_tick).tolist() == [1, 0]


def test_script_snapshot_node_index_beyond_edge_count():
    """Regression: compile_script used to crash with IndexError when a
    snapshot initiator's node index exceeded the edge count (the eager
    edge-table lookup saw a node index)."""
    from chandy_lamport_tpu.core.spec import SnapshotEvent, TickEvent
    from chandy_lamport_tpu.utils.fixtures import TopologySpec

    spec = TopologySpec([("N1", 5), ("N2", 0), ("N3", 0), ("N4", 0)],
                        [("N1", "N2"), ("N2", "N3"), ("N3", "N4")])
    gs = GraphShardedRunner(spec, SimConfig(max_ticks=50), _graph_mesh(2),
                            fixed_delay=1)
    script = gs.compile_script([SnapshotEvent("N4"), TickEvent(1)])
    kind = np.asarray(script.kind).ravel()
    loc = np.asarray(script.loc).ravel()
    shard = np.asarray(script.shard).ravel()
    snap_slots = kind == 2
    assert loc[snap_slots].tolist() == [3]     # node index preserved
    assert shard[snap_slots].tolist() == [-1]  # snapshots carry no shard


def test_combined_data_graph_lanes_match_single_instance():
    """run_storm_batched on a 2-D (data x graph) mesh: with a fixed delay
    every lane must equal the single-instance graph-sharded run."""
    from jax.sharding import Mesh

    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        staggered_snapshots,
        storm_program,
    )

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh2d = Mesh(devs, ("data", "graph"))
    spec = erdos_renyi(8, 2.5, seed=3, tokens=40)
    cfg = SimConfig(max_snapshots=4)

    single = GraphShardedRunner(spec, cfg, _graph_mesh(2), fixed_delay=2)
    prog = storm_program(single.topo, phases=4, amount=1,
                         snapshot_phases=staggered_snapshots(single.topo, 2))
    ref = jax.device_get(single.run_storm(
        single.init_state(), np.asarray(prog.amounts), np.asarray(prog.snap)))

    combined = GraphShardedRunner(spec, cfg, mesh2d, fixed_delay=2)
    batch = 4
    final = jax.device_get(combined.run_storm_batched(
        combined.init_batch(batch), np.asarray(prog.amounts),
        np.asarray(prog.snap)))

    for name in ("time", "tokens", "q_len", "frozen", "rec_cnt", "log_amt",
                 "rec_start", "rec_end", "completed", "error", "next_sid"):
        want = np.asarray(getattr(ref, name))
        got = np.asarray(getattr(final, name))
        assert got.shape == (batch,) + want.shape, name
        for lane in range(batch):
            np.testing.assert_array_equal(got[lane], want, err_msg=name)


def test_sharded_state_checkpoint_roundtrip(tmp_path):
    from chandy_lamport_tpu.utils.checkpoint import load_state, save_state

    spec = read_topology_file(fixture_path("8nodes.top"))
    script = read_events_file(fixture_path("8nodes-sequential-snapshots.events"))
    gs = GraphShardedRunner(spec, SimConfig(), _graph_mesh(2), fixed_delay=2)
    final = gs.run_script(gs.init_state(), script)

    path = str(tmp_path / "sharded.npz")
    save_state(path, final, meta={"kind": "sharded", "shards": 2})
    restored, meta = load_state(path, gs.init_state())
    assert meta["kind"] == "sharded"
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(final)),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_checkpoint_rejects_dense_state(tmp_path):
    """treedef validation (round-1 ADVICE): a DenseState checkpoint must not
    silently load as a ShardedState."""
    from chandy_lamport_tpu.core.state import DenseTopology, init_state
    from chandy_lamport_tpu.utils.checkpoint import load_state, save_state

    spec = read_topology_file(fixture_path("2nodes.top"))
    dense = init_state(DenseTopology(spec), SimConfig(), ())
    path = str(tmp_path / "dense.npz")
    save_state(path, dense)

    gs = GraphShardedRunner(spec, SimConfig(), _graph_mesh(2), fixed_delay=1)
    with pytest.raises(ValueError):
        load_state(path, gs.init_state())
