"""HashJaxDelay: the fused counter-hash fast-path sampler.

Covers the three properties the bench relies on: draws are uniform over
{1..max_delay} (same distribution as the reference's 1 + Intn(maxDelay),
sim.go:100-102), streams are reproducible and counter-disjoint (draw vs
draw_many), and a batched storm under the hash sampler completes with
per-lane conservation and diverging lanes — mirroring the UniformJaxDelay
test above it in test_batched.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import decode_snapshot
from chandy_lamport_tpu.ops.delay_jax import HashJaxDelay, UniformJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner, compile_events
from chandy_lamport_tpu.utils.fixtures import (
    read_events_file,
    read_topology_file,
)
from chandy_lamport_tpu.utils.goldens import fixture_path


def test_hash_delay_range_and_distribution():
    d = HashJaxDelay(seed=123, max_delay=5)
    st = d.init_state()
    rts, st = d.draw_many(st, jnp.int32(0), 50_000)
    delays = np.asarray(rts) - 1  # time=0 -> rt = 1 + delay offset in {0..4}
    assert delays.min() >= 0 and delays.max() <= 4
    counts = np.bincount(delays, minlength=5)
    # 50k draws, p=0.2: expect 10k per bucket, 5 sigma ~ 450
    assert np.all(np.abs(counts - 10_000) < 600), counts


def test_hash_delay_reproducible_and_counter_disjoint():
    d = HashJaxDelay(seed=7)
    st = d.init_state()
    a, st_a = d.draw_many(st, jnp.int32(3), (4, 6))
    b, _ = d.draw_many(d.init_state(), jnp.int32(3), (4, 6))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # sequential scalar draws consume the same counters as one bulk draw
    st2 = d.init_state()
    singles = []
    for _ in range(8):
        rt, st2 = d.draw(st2, jnp.int32(3))
        singles.append(int(rt))
    bulk, _ = d.draw_many(d.init_state(), jnp.int32(3), 8)
    assert singles == list(np.asarray(bulk))
    # the follow-up draw starts where the bulk draw stopped
    follow, _ = d.draw_many(st_a, jnp.int32(3), 2)
    tail, _ = d.draw_many(d.init_state(), jnp.int32(3), 26)
    np.testing.assert_array_equal(np.asarray(follow),
                                  np.asarray(tail)[24:])


def test_hash_delay_lane_keys_injective_and_lane0_matches_single():
    """init_batch_state: no two lanes can share a key (lane -> key is
    injective mod 2^32), and lane 0 reproduces the single-instance
    stream."""
    d = HashJaxDelay(seed=42)
    keys, ctrs, epochs = d.init_batch_state(4096)
    assert len(np.unique(np.asarray(keys))) == 4096
    assert int(np.asarray(ctrs).sum()) == 0
    single, _ = d.draw_many(d.init_state(), jnp.int32(5), 64)
    lane0, _ = d.draw_many((keys[0], ctrs[0], epochs[0]), jnp.int32(5), 64)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(lane0))


def test_hash_delay_counter_wrap_rekeys_stream():
    """ADVICE r3: the uint32 counter wrapping must NOT silently replay the
    per-lane stream — the epoch word re-keys it. Elements of one draw_many
    straddling the wrap get the post-wrap epoch, and the post-wrap stream
    differs from the epoch-0 stream at the same counters."""
    d = HashJaxDelay(seed=5)
    key, _, _ = d.init_state()
    near_wrap = (key, jnp.uint32(2**32 - 4), jnp.uint32(0))
    _, (_, ctr2, ep2) = d.draw_many(near_wrap, jnp.int32(0), 8)
    assert int(ctr2) == 4 and int(ep2) == 1          # wrapped once
    # the post-wrap draws run at epoch 1 — same key, same counters 0..N,
    # different stream than epoch 0 (256 draws can't all coincide)
    rts_long, _ = d.draw_many(near_wrap, jnp.int32(0), 260)
    epoch0_long, _ = d.draw_many(d.init_state(), jnp.int32(0), 256)
    assert not np.array_equal(np.asarray(rts_long)[4:],
                              np.asarray(epoch0_long))
    # scalar draw across the wrap advances the epoch too
    _, st = d.draw((key, jnp.uint32(2**32 - 1), jnp.uint32(0)), jnp.int32(0))
    assert int(st[1]) == 0 and int(st[2]) == 1


def test_hash_delay_distinct_seeds_distinct_streams():
    a, _ = HashJaxDelay(seed=1).draw_many(
        HashJaxDelay(seed=1).init_state(), jnp.int32(0), 256)
    b, _ = HashJaxDelay(seed=2).draw_many(
        HashJaxDelay(seed=2).init_state(), jnp.int32(0), 256)
    assert not np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # ~10 s; test_hash_delay_matches_uniform_summary_shape keeps
# a hash-delay batched storm in tier-1, and conservation is asserted by
# every tier-1 storm summary
def test_hash_delay_storm_lanes_conserve_tokens():
    """Same invariant suite as the UniformJaxDelay lane test
    (test_batched.py): every lane completes every snapshot, conserves
    tokens, and lanes diverge (per-lane seeds really differ)."""
    topo_spec = read_topology_file(fixture_path("10nodes.top"))
    events = read_events_file(fixture_path("10nodes.events"))
    b = 8
    runner = BatchedRunner(topo_spec, SimConfig(queue_capacity=32),
                           HashJaxDelay(seed=99), batch=b)
    script = compile_events(runner.topo, events)
    host = jax.device_get(runner.run(runner.init_batch(), script))

    assert int(host.error.sum()) == 0
    total0 = int(runner.topo.tokens0.sum())
    n = runner.topo.n
    lanes_diverged = False
    for i in range(b):
        lane = jax.tree_util.tree_map(lambda x: x[i], host)
        assert int(lane.q_len.sum()) == 0
        assert int(lane.tokens.sum()) == total0
        for sid in range(int(lane.next_sid)):
            assert int(lane.completed[sid]) == n
            snap = decode_snapshot(runner.topo, lane, sid)
            frozen = sum(snap.token_map.values())
            recorded = sum(m.message.data for m in snap.messages)
            assert frozen + recorded == total0
        if i and not np.array_equal(lane.frozen, host.frozen[0]):
            lanes_diverged = True
    assert lanes_diverged


def test_hash_delay_matches_uniform_summary_shape():
    """The hash sampler drops into BatchedRunner wherever UniformJaxDelay
    does: same storm, same summarize keys, clean completion."""
    from chandy_lamport_tpu.models.workloads import (
        scale_free,
        staggered_snapshots,
        storm_program,
    )

    spec = scale_free(64, 2, seed=3, tokens=40)
    cfg = SimConfig.for_workload(snapshots=4)
    for delay in (UniformJaxDelay(seed=17), HashJaxDelay(seed=17)):
        runner = BatchedRunner(spec, cfg, delay, batch=4, scheduler="sync")
        prog = storm_program(
            runner.topo, phases=8, amount=1,
            snapshot_phases=staggered_snapshots(runner.topo, 4, 1, 2,
                                                max_phases=8))
        summary = BatchedRunner.summarize(
            runner.run_storm(runner.init_batch_device(), prog))
        assert summary["error_bits"] == 0
        assert summary["snapshots_completed"] == summary["snapshots_started"]
