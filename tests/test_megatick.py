"""Differential coverage for the fused multi-tick engine (ops/tick
TickKernel megatick) and the batched wave-exact path.

Two claims are pinned here, both bit-level:

1. A K-tick megatick dispatch (``run_ticks`` — lax.scan-fused steps with
   the cumulative quiescence mask and the O(1) drained-stretch
   fast-forward) is bit-identical to K sequential ``tick`` calls, for K
   spanning sub-megatick, one-megatick and multi-megatick counts and for
   runs that cross the quiescence boundary mid-scan.

2. The fused/batched wave-exact path (BatchedRunner scheduler='exact',
   exact_impl='wave', compiled scripts with multi-tick stretches, the
   megatick drain) reproduces the sequential cascade oracle
   (DenseSim megatick=1 — the reference-literal one-iteration-per-tick
   loops) bit-exactly on the event scripts of all 7 reference goldens.
"""

import random

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.dense import DenseSim
from chandy_lamport_tpu.core.state import DenseTopology, init_state
from chandy_lamport_tpu.models.workloads import ring_topology
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, HashJaxDelay
from chandy_lamport_tpu.ops.tick import TickKernel
from chandy_lamport_tpu.parallel.batch import BatchedRunner, compile_events
from chandy_lamport_tpu.utils.compare import dense_state_mismatches
from chandy_lamport_tpu.utils.fixtures import (
    read_events_file,
    read_topology_file,
)
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path
from chandy_lamport_tpu.utils.randgen import random_strongly_connected


def _assert_identical(a, b):
    assert dense_state_mismatches(a, b) == []


def _loaded(megatick, exact_impl="cascade", seed=7):
    """A kernel + state carrying live traffic and one snapshot in flight
    (deterministic: every construction of the same args is identical;
    strongly connected so the drain test cannot run to max_ticks)."""
    topo = DenseTopology(random_strongly_connected(random.Random(11), 10))
    cfg = SimConfig(max_snapshots=4, queue_capacity=32, max_recorded=64)
    delay = HashJaxDelay(seed=seed)
    kern = TickKernel(topo, cfg, delay, exact_impl=exact_impl,
                      megatick=megatick)
    s = init_state(topo, cfg, delay.init_state())
    for e in range(0, topo.e, 3):
        s = kern.inject_send(s, np.int32(e), np.int32(2))
    s = kern.inject_snapshot(s, np.int32(0))
    return kern, s


@pytest.mark.parametrize("k", [1, 3, 17])
@pytest.mark.parametrize("impl", ["cascade", "wave"])
def test_megatick_matches_sequential_ticks(k, impl):
    """K fused ticks == K sequential ticks, every state plane and the
    sampler stream position included. K=17 runs past the drain point of
    this workload, so the largest case also exercises the fast-forward."""
    kern_m, s_m = _loaded(megatick=8, exact_impl=impl)
    s_m = kern_m.run_ticks(s_m, np.int32(k))

    kern_s, s_s = _loaded(megatick=8, exact_impl=impl)
    for _ in range(k):
        s_s = kern_s.tick(s_s)

    a, b = jax.device_get(s_m), jax.device_get(s_s)
    assert int(a.time) == k
    _assert_identical(a, b)


def test_megatick_crosses_quiescence_boundary_mid_scan():
    """One delivery at tick 1, then nothing in flight: the quiescence
    boundary falls inside the first megatick, the rest of the run is
    fast-forwarded — and the result must still be bit-identical to 17
    sequential ticks (time advanced the full 17, nothing else moved)."""
    topo = DenseTopology(ring_topology(4, tokens=20))
    cfg = SimConfig(max_snapshots=2, queue_capacity=8, max_recorded=16)

    def build(megatick):
        delay = FixedJaxDelay(1)
        kern = TickKernel(topo, cfg, delay, exact_impl="cascade",
                          megatick=megatick)
        s = init_state(topo, cfg, delay.init_state())
        return kern, kern.inject_send(s, np.int32(0), np.int32(3))

    kern_m, s_m = build(megatick=8)
    s_m = kern_m.run_ticks(s_m, np.int32(17))
    kern_s, s_s = build(megatick=8)
    for _ in range(17):
        s_s = kern_s.tick(s_s)

    a, b = jax.device_get(s_m), jax.device_get(s_s)
    assert int(a.time) == 17
    assert int(np.sum(a.q_len)) == 0      # genuinely quiescent at the end
    _assert_identical(a, b)


def test_megatick_resumes_after_fastforward():
    """Inject -> fused run past quiescence -> inject again -> fused run:
    the fast-forwarded state must accept new traffic exactly like the
    sequentially ticked one (guards against a fast-forward that corrupts
    anything beyond time)."""
    def run(fused):
        kern, s = _loaded(megatick=8 if fused else 1)
        s = kern.run_ticks(s, np.int32(25))
        s = kern.inject_send(s, np.int32(1), np.int32(4))
        s = kern.inject_snapshot(s, np.int32(2))
        return jax.device_get(kern.run_ticks(s, np.int32(9)))

    _assert_identical(run(fused=True), run(fused=False))


def test_megatick_drain_matches_unfused_drain():
    """The fused drain (K drain ticks per while iteration, each scan step
    re-checking the drain condition) stops at exactly the same tick and
    state as the one-tick-per-iteration drain."""
    def run(megatick):
        kern, s = _loaded(megatick=megatick, exact_impl="wave")
        return jax.device_get(kern.drain_and_flush(s))

    a, b = run(8), run(1)
    _assert_identical(a, b)
    assert int(np.sum(a.q_len)) == 0


_GOLDEN_IDS = [events.removesuffix(".events")
               for _, events, _ in REFERENCE_TESTS]


_TIER1_GOLDENS = {"3nodes-simple"}


@pytest.mark.parametrize(
    "top,events",
    # each golden case costs a ~8-15s compile; one representative small
    # fixture + the hash-delay lane-0 test below keep the wave-vs-cascade
    # differential in tier-1, the other six goldens run in full passes
    [pytest.param(t, e, marks=([]
                               if e.removesuffix(".events") in _TIER1_GOLDENS
                               else [pytest.mark.slow]))
     for t, e, _ in REFERENCE_TESTS],
    ids=_GOLDEN_IDS)
def test_batched_wave_matches_sequential_cascade_on_goldens(top, events):
    """All 7 reference golden scripts through the fused/batched wave path
    (vmapped wave tick, compiled script with multi-tick stretches, fused
    megatick drain) vs the sequential cascade oracle (DenseSim,
    megatick=1). FixedJaxDelay makes every lane's stream identical to the
    single-instance stream, so EVERY lane must be bit-identical to the
    oracle's final state — not just decode-equal."""
    spec = read_topology_file(fixture_path(top))
    evs = read_events_file(fixture_path(events))
    cfg = SimConfig(max_snapshots=16, queue_capacity=64, max_recorded=64)
    batch = 4

    oracle = DenseSim(spec, FixedJaxDelay(2), cfg, exact_impl="cascade",
                      megatick=1)
    oracle.run_events(evs)
    ref = oracle._host()

    runner = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=batch,
                           scheduler="exact", exact_impl="wave")
    final = jax.device_get(
        runner.run(runner.init_batch(), compile_events(runner.topo, evs)))
    assert int(np.max(final.error)) == 0
    for lane in range(batch):
        _assert_identical(
            jax.tree_util.tree_map(lambda x: x[lane], final), ref)


@pytest.mark.slow  # ~11 s; the 3nodes-simple golden above + the hash-delay
# summary test in test_hash_delay keep both claims in tier-1
def test_batched_wave_matches_cascade_on_goldens_hash_lane0():
    """Same scripts under the production hash sampler (per-lane streams):
    lane 0 reproduces the single-instance stream exactly, so the batched
    wave's lane 0 must bit-match the sequential cascade. One combined case
    keeps the tier-1 budget flat (7 separate compiles would not)."""
    top, events, _ = REFERENCE_TESTS[5]          # 8nodes-concurrent: densest
    spec = read_topology_file(fixture_path(top))
    evs = read_events_file(fixture_path(events))
    cfg = SimConfig(max_snapshots=16, queue_capacity=64, max_recorded=64)

    oracle = DenseSim(spec, HashJaxDelay(31), cfg, exact_impl="cascade",
                      megatick=1)
    oracle.run_events(evs)

    runner = BatchedRunner(spec, cfg, HashJaxDelay(31), batch=4,
                           scheduler="exact", exact_impl="wave")
    final = jax.device_get(
        runner.run(runner.init_batch(), compile_events(runner.topo, evs)))
    assert int(np.max(final.error)) == 0
    _assert_identical(jax.tree_util.tree_map(lambda x: x[0], final),
                      oracle._host())


def test_compiled_script_carries_tick_counts():
    """compile_events folds ``tick n`` into per-phase COUNTS (no more
    one-empty-phase-per-tick expansion): 3nodes-simple's ``tick`` +
    ``tick 4`` + trailing send compile to do_tick [1, 4, 0]."""
    spec = read_topology_file(fixture_path("3nodes.top"))
    evs = read_events_file(fixture_path("3nodes-simple.events"))
    script = compile_events(DenseTopology(spec), evs)
    assert np.asarray(script.do_tick).tolist() == [1, 4, 0]
    assert script.kind.shape[0] == 3
