"""The one-kernel megatick (kernels/megatick.py, SimConfig.fused_tick):
bit-identity, gating, and pipeline edge geometry.

Three claims, all on the CPU mesh via interpret-mode Pallas:

1. The fused K-tick kernel — the whole tick body lax.scanned inside ONE
   pallas_call with the state VMEM-resident and the per-(tick,edge)
   fault masks DMA-streamed in edge blocks — is bit-identical to the
   split-kernel path (and, via the goldens, to the XLA oracle) on every
   plane, including fault books, error bits, and the sampler stream
   position.

2. ``resolve_fused_tick`` gates honestly: "auto" engages exactly when
   the documented requirements hold (including the supervisor and
   flight-recorder arms, whose historical refusals ISSUE-16 lifted),
   and "on" raises naming ALL unmet requirements at once instead of
   making users discover them one error at a time.

3. The double-buffered HBM->VMEM mask pipeline survives every edge-
   geometry corner: E not divisible by the block width, single-edge
   graphs, capacity-1 rings, markers landing exactly on a DMA block
   boundary, and K far past quiescence (the fast-forward prefix).

The heaviest legs (full golden sweep x impl x queue engine, deep fault
matrices) are slow-marked; tier-1 keeps one arm per axis plus the shared
``fused_pair10`` session fixture (conftest.py) so the expensive fused
compile is paid once.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.dense import DenseSim
from chandy_lamport_tpu.core.state import DenseTopology, init_state
from chandy_lamport_tpu.kernels import megatick as plk
from chandy_lamport_tpu.models.faults import JaxFaults
from chandy_lamport_tpu.models.workloads import ring_topology
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, HashJaxDelay
from chandy_lamport_tpu.ops.tick import TickKernel
from chandy_lamport_tpu.utils.compare import dense_state_mismatches
from chandy_lamport_tpu.utils.fixtures import (
    read_events_file,
    read_topology_file,
)
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path
from chandy_lamport_tpu.utils.randgen import random_strongly_connected


def _assert_identical(a, b):
    assert dense_state_mismatches(jax.device_get(a), jax.device_get(b)) == []


def _pair(exact_impl="cascade", queue_engine="auto", megatick=4,
          block_edges=5, faults=None, n=10, cfg=None, spec=None, seed=7):
    """A (split, fused, loaded state) triple on the strongly-connected
    10-node graph (the conftest fixture's recipe, parameterizable)."""
    topo = DenseTopology(spec if spec is not None
                         else random_strongly_connected(random.Random(11), n))
    cfg = cfg or SimConfig(max_snapshots=4, queue_capacity=32,
                           max_recorded=64)
    delay = HashJaxDelay(seed=seed)

    def mk(fused):
        return TickKernel(topo, cfg, delay, exact_impl=exact_impl,
                          megatick=megatick, queue_engine=queue_engine,
                          kernel_engine="pallas", faults=faults,
                          quarantine=faults is not None,
                          fused_tick=fused, fused_block_edges=block_edges)

    split, fused = mk("off"), mk("on")
    s = init_state(topo, cfg, delay.init_state(),
                   fault_key=int(faults.init_state()) if faults else 0)
    for e in range(0, topo.e, 3):
        s = split.inject_send(s, np.int32(e), np.int32(2))
    s = split.inject_snapshot(s, np.int32(0))
    # host-side: the jitted entry points donate their state argument
    return split, fused, jax.device_get(s)


# ---------------------------------------------------------------------------
# resolution gate + block planning (pure functions, no compile)


def test_plan_edge_blocks_geometry():
    # E divisible, E ragged, E smaller than one block, degenerate E=1
    assert plk.plan_edge_blocks(1024, 512) == (2, 512)
    assert plk.plan_edge_blocks(21, 5) == (5, 5)      # last block ragged
    assert plk.plan_edge_blocks(3, 512) == (1, 3)     # clamped to E
    assert plk.plan_edge_blocks(1, 0) == (1, 1)
    with pytest.raises(ValueError):
        plk.plan_edge_blocks(0)


def test_resolve_fused_tick_auto_gate():
    base = dict(kernel_engine="pallas", megatick=4, marker_mode="ring",
                exact_impl="cascade", supervised=False, traced=False,
                vmem_bytes=1 << 20)
    on, why = plk.resolve_fused_tick("auto", **base)
    assert on == "on" and "engaged" in why
    for knob, bad, word in (
            ("kernel_engine", "xla", "kernel_engine"),
            ("megatick", 1, "megatick"),
            ("marker_mode", "split", "marker"),
            ("exact_impl", "fold", "exact_impl"),
            ("vmem_bytes", plk.FUSED_VMEM_BUDGET + 1, "VMEM")):
        off, why = plk.resolve_fused_tick("auto", **{**base, knob: bad})
        assert off == "off", knob
        assert word.lower() in why.lower(), (knob, why)
    # the supervisor and flight-recorder arms ENGAGE — the historical
    # refusals are lifted (both trace as masked lane ops in-kernel)
    for knob in ("supervised", "traced"):
        on, why = plk.resolve_fused_tick("auto", **{**base, knob: True})
        assert on == "on", (knob, why)
    # an over-budget resident set engages anyway when the TILED working
    # set fits (the ring planes stream); refuses only when tiled is
    # over too, or tiling is forbidden (tiled_vmem_bytes=None)
    big = dict(base, vmem_bytes=plk.FUSED_VMEM_BUDGET + 1)
    on, why = plk.resolve_fused_tick(
        "auto", **big, tiled_vmem_bytes=plk.FUSED_VMEM_BUDGET - 1)
    assert on == "on", why
    off, why = plk.resolve_fused_tick(
        "auto", **big, tiled_vmem_bytes=plk.FUSED_VMEM_BUDGET + 1)
    assert off == "off" and "tiled" in why
    assert plk.resolve_fused_tick("off", **base) == ("off", "fused_tick='off'")


def test_resolve_fused_tick_on_raises_naming_requirement():
    base = dict(kernel_engine="pallas", megatick=4, marker_mode="ring",
                exact_impl="cascade", supervised=False, traced=False,
                vmem_bytes=1 << 20)
    with pytest.raises(ValueError, match="kernel_engine"):
        plk.resolve_fused_tick("on", **{**base, "kernel_engine": "xla"})
    with pytest.raises(ValueError, match="megatick"):
        plk.resolve_fused_tick("on", **{**base, "megatick": 1})
    # ALL unmet requirements in one error, counted and named
    with pytest.raises(ValueError) as ei:
        plk.resolve_fused_tick("on", **{**base, "kernel_engine": "xla",
                                        "megatick": 1,
                                        "marker_mode": "split"})
    msg = str(ei.value)
    assert "3 unmet requirement(s)" in msg
    for word in ("kernel_engine", "megatick", "marker_mode"):
        assert word in msg, msg
    with pytest.raises(ValueError, match="unknown fused_tick"):
        plk.resolve_fused_tick("sideways", **base)


def test_fused_vmem_budget_math():
    # the documented line items: carry + slack, plus the streaming
    # scratch (2 slots x 8 rows x NB*EB words) and the [K,2,N] node
    # plane only when the adversary is armed
    base = plk.fused_vmem_bytes(1000, e=21, n=10, length=4, faulted=False)
    assert base == 1000 + 64
    nb, eb = plk.plan_edge_blocks(21, 5)
    armed = plk.fused_vmem_bytes(1000, e=21, n=10, length=4, faulted=True,
                                 block_edges=5)
    assert armed == 1000 + 64 + 2 * 8 * nb * eb * 4 + 4 * 2 * 10 * 4


# ---------------------------------------------------------------------------
# bit-identity: fused vs split (the shared fixture pays the compile once)


@pytest.mark.slow  # ~13 s; the golden oracle + past-quiescence tests keep fused==split tier-1
def test_fused_matches_split_run_and_drain(fused_pair10):
    split, fused, s = fused_pair10
    _assert_identical(fused.run_ticks(s, np.int32(9)),
                      split.run_ticks(s, np.int32(9)))
    _assert_identical(fused.drain_and_flush(s), split.drain_and_flush(s))


@pytest.mark.slow
def test_fused_matches_split_under_jit_vmap(fused_pair10):
    """The batched regime: the fused kernel under jit(vmap(.)), per-lane
    states differing in load. Bit-identity must hold lane-wise."""
    split, fused, s = fused_pair10
    batch = jax.tree_util.tree_map(
        lambda x: jnp.stack([x, x]), jax.device_get(s))
    ran_f = jax.jit(jax.vmap(lambda t: fused._run_ticks(t, jnp.int32(6))))(
        batch)
    ran_s = jax.jit(jax.vmap(lambda t: split._run_ticks(t, jnp.int32(6))))(
        batch)
    for lane in range(2):
        _assert_identical(
            jax.tree_util.tree_map(lambda x: x[lane], ran_f),
            jax.tree_util.tree_map(lambda x: x[lane], ran_s))


@pytest.mark.slow
@pytest.mark.parametrize("impl,qe", [("wave", "mask"), ("wave", "gather"),
                                     ("cascade", "mask")])
def test_fused_matches_split_other_arms(impl, qe):
    """The off-diagonal impl x queue-engine arms (the fixture covers
    cascade/gather, the tier-1 golden re-covers it end-to-end; these
    ride in full passes — each pays a fresh ~25 s fused compile)."""
    split, fused, s = _pair(exact_impl=impl, queue_engine=qe)
    _assert_identical(fused.run_ticks(s, np.int32(9)),
                      split.run_ticks(s, np.int32(9)))
    _assert_identical(fused.drain_and_flush(s), split.drain_and_flush(s))


@pytest.mark.slow
def test_fused_matches_split_with_message_faults():
    """The in-kernel fault gates — masked lanes driven by the streamed
    per-(tick,edge) planes — vs the split path's per-tick hash draws:
    identical books (fault_counts), identical state. (Tier-1's fault
    sentinel is test_fused_marker_on_block_boundary below — marker
    faults across the DMA seam, one compile instead of two.)"""
    faults = JaxFaults(3, drop_rate=0.2, dup_rate=0.15, jitter_rate=0.2,
                       marker_drop_rate=0.1, marker_dup_rate=0.15,
                       marker_jitter_rate=0.2)
    split, fused, s = _pair(faults=faults)
    a = fused.drain_and_flush(s)
    b = split.drain_and_flush(s)
    assert int(np.asarray(jax.device_get(a.fault_counts)).sum()) > 0
    _assert_identical(a, b)


@pytest.mark.slow
def test_fused_matches_split_with_crashes_and_quarantine():
    faults = JaxFaults(5, crash_rate=0.3, crash_len=3, crash_period=8,
                       crash_mode="lossy")
    split, fused, s = _pair(faults=faults)
    a = fused.drain_and_flush(s)
    b = split.drain_and_flush(s)
    assert int(np.asarray(jax.device_get(a.fault_counts))[3]) > 0
    _assert_identical(a, b)


# ---------------------------------------------------------------------------
# pipeline edge geometry (all interpret mode, all tier-1)


@pytest.mark.slow
def test_fused_block_width_not_dividing_edge_count():
    """E=21 with EB=4: five full blocks + one ragged block of 1. The
    reconstruction slice must drop exactly the pad lanes. (Tier-1
    already exercises ragged geometry through the shared fixture's
    EB=5-on-21-edges layout; this pins a second width in full passes.)"""
    split, fused, s = _pair(block_edges=4)
    _assert_identical(fused.run_ticks(s, np.int32(5)),
                      split.run_ticks(s, np.int32(5)))


def test_fused_single_edge_graph():
    """E=1 degenerates the pipeline to one single-lane block per tick."""
    from chandy_lamport_tpu.utils.fixtures import TopologySpec
    topo = DenseTopology(TopologySpec([("A", 5), ("B", 0)], [("A", "B")]))
    cfg = SimConfig(max_snapshots=2, queue_capacity=8, max_recorded=16)
    delay = FixedJaxDelay(2)

    def mk(fused):
        return TickKernel(topo, cfg, delay, exact_impl="cascade",
                          megatick=3, kernel_engine="pallas",
                          fused_tick=fused)

    split, fused_k = mk("off"), mk("on")
    s = init_state(topo, cfg, delay.init_state())
    s = split.inject_send(s, np.int32(0), np.int32(2))
    s = jax.device_get(split.inject_snapshot(s, np.int32(0)))
    _assert_identical(fused_k.run_ticks(s, np.int32(7)),
                      split.run_ticks(s, np.int32(7)))


def test_fused_capacity_one_ring():
    """queue_capacity=1: every ring plane is a [E,1] sliver and one
    marker fills an edge; overflow bits (if any) must agree bit-for-bit
    with the split path."""
    cfg = SimConfig(max_snapshots=2, queue_capacity=1, max_recorded=8)
    topo = DenseTopology(ring_topology(4, tokens=4))
    delay = FixedJaxDelay(1)

    def mk(fused):
        return TickKernel(topo, cfg, delay, exact_impl="cascade",
                          megatick=2, kernel_engine="pallas",
                          fused_tick=fused)

    split, fused_k = mk("off"), mk("on")
    s = init_state(topo, cfg, delay.init_state())
    s = jax.device_get(split.inject_snapshot(s, np.int32(0)))
    _assert_identical(fused_k.drain_and_flush(s),
                      split.drain_and_flush(s))


def test_fused_marker_on_block_boundary():
    """Ring of 8 (E=8), EB=4: node 4's out-edge is edge 4 — the first
    lane of DMA block 1 — so the marker's fault-mask lane crosses the
    double-buffer seam exactly at the boundary."""
    faults = JaxFaults(9, marker_drop_rate=0.25, marker_jitter_rate=0.25)
    cfg = SimConfig(max_snapshots=2, queue_capacity=8, max_recorded=16)
    topo = DenseTopology(ring_topology(8, tokens=8))
    delay = HashJaxDelay(seed=13)

    def mk(fused):
        return TickKernel(topo, cfg, delay, exact_impl="cascade",
                          megatick=4, kernel_engine="pallas", faults=faults,
                          quarantine=True, fused_tick=fused,
                          fused_block_edges=4)

    split, fused_k = mk("off"), mk("on")
    s = init_state(topo, cfg, delay.init_state(),
                   fault_key=int(faults.init_state()))
    s = jax.device_get(split.inject_snapshot(s, np.int32(4)))
    _assert_identical(fused_k.drain_and_flush(s),
                      split.drain_and_flush(s))


def test_fused_megatick_past_quiescence(fused_pair10):
    """K=4 megaticks scanned far past this workload's drain point: the
    quiet prefix must fast-forward (time still advances) without
    consuming fault-plane rows differently than the split path."""
    split, fused, s = fused_pair10
    a = fused.run_ticks(s, np.int32(60))
    b = split.run_ticks(s, np.int32(60))
    assert int(jax.device_get(a.time)) == 60
    _assert_identical(a, b)


# ---------------------------------------------------------------------------
# composition + plumbing


def test_fused_auto_engages_for_supervisor_and_trace():
    """The production arms ISSUE-16 un-refused: an armed snapshot
    supervisor and an armed flight recorder no longer knock 'auto' back
    to the split path — both fuse (their ticks trace in-kernel)."""
    topo = DenseTopology(ring_topology(4, tokens=4))
    delay = FixedJaxDelay(2)
    sup_cfg = SimConfig(max_snapshots=2, queue_capacity=8, max_recorded=8,
                        snapshot_timeout=8)
    kern = TickKernel(topo, sup_cfg, delay, megatick=4,
                      kernel_engine="pallas", fused_tick="auto")
    assert kern.fused == "on", kern.fused_reason

    from chandy_lamport_tpu.utils.tracing import JaxTrace
    tr_cfg = SimConfig(max_snapshots=2, queue_capacity=8, max_recorded=8,
                       trace_capacity=16)
    kern = TickKernel(topo, tr_cfg, delay, megatick=4,
                      kernel_engine="pallas", fused_tick="auto",
                      trace=JaxTrace(capacity=16))
    assert kern.fused == "on", kern.fused_reason


def test_fused_knob_surfaces_on_runners():
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    spec = ring_topology(4, tokens=4)
    cfg = SimConfig(max_snapshots=2, queue_capacity=8, max_recorded=8)
    sim = DenseSim(spec, FixedJaxDelay(2), cfg, megatick=4,
                   kernel_engine="pallas", fused_tick="on")
    assert sim.fused == "on"
    runner = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=2,
                           scheduler="exact", megatick=4,
                           kernel_engine="pallas", fused_tick="on")
    assert runner.fused == "on"
    # xla engine: "auto" resolves off with the engine named
    runner = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=2,
                           scheduler="exact", megatick=4,
                           kernel_engine="xla", fused_tick="auto")
    assert runner.fused == "off"
    assert "kernel_engine" in runner.fused_reason


def test_graphshard_refuses_fused_on():
    from jax.sharding import Mesh

    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
    spec = ring_topology(8, tokens=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("graph",))
    gs = GraphShardedRunner(spec, SimConfig(max_snapshots=2), mesh)
    assert gs.fused == "off" and "shard" in gs.fused_reason
    with pytest.raises(ValueError, match="fused_tick='on' impossible"):
        GraphShardedRunner(spec, SimConfig(max_snapshots=2), mesh,
                           fused_tick="on")


# ---------------------------------------------------------------------------
# goldens: fused vs the XLA oracle on the reference scripts

_GOLDEN_IDS = [e.removesuffix(".events") for _, e, _ in REFERENCE_TESTS]


def _golden_diff(top, events, impl, qe):
    spec = read_topology_file(fixture_path(top))
    evs = read_events_file(fixture_path(events))
    cfg = SimConfig(max_snapshots=16, queue_capacity=64, max_recorded=64)

    oracle = DenseSim(spec, FixedJaxDelay(2), cfg, exact_impl=impl,
                      megatick=1, kernel_engine="xla")
    snaps_ref = oracle.run_events(evs)

    fused = DenseSim(spec, FixedJaxDelay(2), cfg, exact_impl=impl,
                     megatick=4, queue_engine=qe, kernel_engine="pallas",
                     fused_tick="on")
    assert fused.fused == "on"
    snaps = fused.run_events(evs)
    _assert_identical(fused.state, oracle.state)
    assert snaps == snaps_ref


def test_golden_fused_matches_xla_oracle_tier1():
    """One golden through the fused engine vs the unfused XLA oracle:
    the cheap tier-1 sentinel for the full slow sweep below."""
    top, events, _ = REFERENCE_TESTS[2]            # 3nodes-simple
    _golden_diff(top, events, "cascade", "gather")


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["cascade", "wave"])
@pytest.mark.parametrize("qe", ["gather", "mask"])
@pytest.mark.parametrize("top,events",
                         [(t, e) for t, e, _ in REFERENCE_TESTS],
                         ids=_GOLDEN_IDS)
def test_golden_fused_matches_xla_oracle_full(top, events, impl, qe):
    """The acceptance sweep: all 7 goldens x {cascade,wave} x
    {gather,mask}, fused vs the sequential XLA oracle — decoded
    snapshots AND every final state plane."""
    _golden_diff(top, events, impl, qe)
