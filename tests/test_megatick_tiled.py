"""The TILED megatick (ISSUE-16, kernels/megatick.py module docstring):
state-plane double-buffering past the VMEM ceiling, and the un-refused
production arms (supervisor, flight recorder, serve) riding it.

Three claims:

1. Geometry and resolution are exact at the byte: ``fused_vmem_bytes``'s
   tiled working set matches its documented line items, the
   ``ring_append_slots`` census matches the per-arm append bound, and
   the ``resolve_fused_tick``/``resolve_fused_tile`` pair flips at
   EXACTLY the budget boundary — at-budget stays resident, one byte
   over streams the rings, 10x over (tiled set over too) refuses.

2. The tiled layout is bit-identical to the resident fused kernel AND
   the split path on every plane — including the arms whose refusals
   this issue lifted (armed supervisor, snapshot daemon, flight
   recorder, and the serve step) and the DMA-schedule corners (single
   ring block, markers landing on a ring-block seam, fault dup
   re-appends).

3. A shape whose resident working set exceeds the 12 MB budget — which
   previously resolved fused_tick='auto' to "off" — now engages with
   ``fused_tile="on"`` and stays bit-identical to the split path.

Tier-1 keeps the pure geometry tests plus two differential sentinels
(one tiled arm with seam-crossing markers, one supervised tiled arm);
the full arm sweep, the over-budget shape, and the serving/stream
differentials are slow-marked.
"""

import random

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import DenseTopology, init_state
from chandy_lamport_tpu.kernels import megatick as plk
from chandy_lamport_tpu.models.faults import JaxFaults
from chandy_lamport_tpu.models.workloads import ring_topology
from chandy_lamport_tpu.ops.delay_jax import HashJaxDelay
from chandy_lamport_tpu.ops.tick import TickKernel
from chandy_lamport_tpu.utils.compare import dense_state_mismatches
from chandy_lamport_tpu.utils.randgen import random_strongly_connected


def _assert_identical(a, b):
    assert dense_state_mismatches(jax.device_get(a), jax.device_get(b)) == []


def _diff_arm(cfg, impl="cascade", tile="on", block_edges=5, faults=None,
              trace=None, ticks=9, n=10, megatick=4, drain=True):
    """split vs fused(tile) on the strongly-connected 10-node graph:
    run_ticks (and, unless ``drain=False``, drain_and_flush), every
    state plane."""
    topo = DenseTopology(random_strongly_connected(random.Random(11), n))
    delay = HashJaxDelay(seed=7)

    def mk(fused):
        return TickKernel(topo, cfg, delay, exact_impl=impl,
                          megatick=megatick, queue_engine="auto",
                          kernel_engine="pallas", faults=faults,
                          quarantine=faults is not None, trace=trace,
                          fused_tick=fused, fused_block_edges=block_edges,
                          fused_tile=tile)

    split, fused = mk("off"), mk("on")
    assert fused.fused == "on", fused.fused_reason
    assert fused.fused_tile == tile
    s = init_state(topo, cfg, delay.init_state(),
                   fault_key=int(faults.init_state()) if faults else 0)
    for e in range(0, topo.e, 3):
        s = split.inject_send(s, np.int32(e), np.int32(2))
    s = split.inject_snapshot(s, np.int32(0))
    s = jax.device_get(s)
    _assert_identical(fused.run_ticks(s, np.int32(ticks)),
                      split.run_ticks(s, np.int32(ticks)))
    if drain:
        _assert_identical(fused.drain_and_flush(s),
                          split.drain_and_flush(s))


_BASE = dict(max_snapshots=4, queue_capacity=32, max_recorded=64)


# ---------------------------------------------------------------------------
# geometry + resolution (pure functions, no compile)


def test_ring_append_slots_census():
    # marker waves bounded by min(S, in_degree), floor 1
    assert plk.ring_append_slots(max_snapshots=4, max_in_degree=2,
                                 timeout_armed=False, every_armed=False,
                                 faulted=False) == 2
    assert plk.ring_append_slots(max_snapshots=1, max_in_degree=8,
                                 timeout_armed=False, every_armed=False,
                                 faulted=False) == 1
    # supervisor retries add S, the daemon 1, the fault dup 1
    assert plk.ring_append_slots(max_snapshots=4, max_in_degree=2,
                                 timeout_armed=True, every_armed=True,
                                 faulted=True) == 2 + 4 + 1 + 1
    assert plk.ring_append_slots(max_snapshots=0, max_in_degree=0,
                                 timeout_armed=False, every_armed=False,
                                 faulted=False) == 1          # floor


def test_tiled_vmem_budget_math():
    # the documented tiled line items: rings leave the carry, replaced
    # by the 2-slot x 2-plane [EB, C] DMA scratch, the [A, E] x 3
    # deferred-append planes, and the two [E] head vectors
    e, c, a, be = 21, 32, 3, 5
    base = plk.fused_vmem_bytes(10_000, e=e, n=10, length=4, faulted=False)
    tiled = plk.fused_vmem_bytes(10_000, e=e, n=10, length=4,
                                 faulted=False, block_edges=be,
                                 tiled=True, queue_capacity=c,
                                 append_slots=a)
    nb, eb = plk.plan_edge_blocks(e, be)
    assert tiled == (base - 2 * e * c * 4 + 2 * 2 * eb * c * 4
                     + 3 * a * e * 4 + 2 * e * 4)
    with pytest.raises(ValueError, match="queue_capacity"):
        plk.fused_vmem_bytes(10_000, e=e, n=10, length=4, faulted=False,
                             tiled=True)


def test_resolve_tile_at_budget_boundaries():
    budget = plk.FUSED_VMEM_BUDGET
    base = dict(kernel_engine="pallas", megatick=4, marker_mode="ring",
                exact_impl="cascade", supervised=False, traced=False)
    # exactly AT the budget: fused engages, tiling would add ring DMA
    # for nothing — auto stays resident
    on, _ = plk.resolve_fused_tick("auto", **base, vmem_bytes=budget,
                                   tiled_vmem_bytes=budget // 2)
    tile, why = plk.resolve_fused_tile("auto", fused=on, vmem_bytes=budget,
                                       tiled_vmem_bytes=budget // 2)
    assert (on, tile) == ("on", "off") and "fits" in why
    # ONE BYTE over: the rings stream
    on, _ = plk.resolve_fused_tick("auto", **base, vmem_bytes=budget + 1,
                                   tiled_vmem_bytes=budget // 2)
    tile, why = plk.resolve_fused_tile("auto", fused=on,
                                       vmem_bytes=budget + 1,
                                       tiled_vmem_bytes=budget // 2)
    assert (on, tile) == ("on", "on") and "stream" in why
    # 10x over, tiled set over too: honest refusal naming both figures
    off, why = plk.resolve_fused_tick("auto", **base,
                                      vmem_bytes=budget * 10,
                                      tiled_vmem_bytes=budget * 9)
    assert off == "off" and "tiled" in why
    with pytest.raises(ValueError, match="tiled"):
        plk.resolve_fused_tick("on", **base, vmem_bytes=budget * 10,
                               tiled_vmem_bytes=budget * 9)
    # tiling forbidden (fused_tile='off' upstream -> tiled bytes None)
    off, why = plk.resolve_fused_tick("auto", **base,
                                      vmem_bytes=budget + 1,
                                      tiled_vmem_bytes=None)
    assert off == "off" and "fused_tile='off'" in why
    # no kernel to tile when the fused megatick itself is off
    tile, why = plk.resolve_fused_tile("auto", fused="off",
                                       vmem_bytes=0, tiled_vmem_bytes=0)
    assert tile == "off" and "no kernel" in why
    with pytest.raises(ValueError, match="unknown fused_tile"):
        plk.resolve_fused_tile("sideways", fused="on", vmem_bytes=0,
                               tiled_vmem_bytes=0)


def test_pack_ring_plane_geometry():
    import jax.numpy as jnp
    plane = jnp.arange(21 * 4, dtype=jnp.int32).reshape(21, 4)
    nb, eb = plk.plan_edge_blocks(21, 5)
    packed = plk._pack_ring_plane(plane, nb, eb)
    assert packed.shape == (nb, eb, 4)
    flat = np.asarray(packed).reshape(nb * eb, 4)
    assert np.array_equal(flat[:21], np.asarray(plane))     # edges intact
    assert (flat[21:] == 0).all()                           # pads zero


def test_ring_heads_matches_gather():
    import jax.numpy as jnp
    rng = np.random.RandomState(3)
    qm = jnp.asarray(rng.randint(0, 1 << 20, (7, 16)), jnp.int32)
    qd = jnp.asarray(rng.randint(0, 1 << 20, (7, 16)), jnp.int32)
    qh = jnp.asarray(rng.randint(0, 16, (7,)), jnp.int32)
    hm, hd = plk.ring_heads(qm, qd, qh)
    assert hm.dtype == jnp.int32 and hd.dtype == jnp.int32
    assert np.array_equal(np.asarray(hm),
                          np.asarray(qm)[np.arange(7), np.asarray(qh)])
    assert np.array_equal(np.asarray(hd),
                          np.asarray(qd)[np.arange(7), np.asarray(qh)])


# ---------------------------------------------------------------------------
# differentials: tier-1 sentinels


@pytest.mark.slow  # ~17 s (the only tier-1 tiled compile); the serve-report
# stamp test below keeps a tiled smoke in tier-1, the seam differential and
# the full sweep run in full passes
def test_tiled_supervised_seam_sentinel():
    """THE tier-1 tiled sentinel, one compile pair for two claims:
    block_edges=5 on the 21-edge graph puts ring-block seams at edges
    4|5, 9|10, 14|15, 19|20 and the snapshot broadcast appends markers
    across every seam (deferred-append commit + head pre-extraction +
    block-boundary DMA hazards), while the armed supervisor's deadline
    arithmetic and retry re-initiation append INSIDE the kernel through
    the same deferred buffers (the head-slot patch threads their
    appends through the lax.cond/while_loop wrappers as carry
    dataflow). The drain differential and the remaining arm matrix run
    in the slow sweep."""
    _diff_arm(SimConfig(snapshot_timeout=5, snapshot_retries=2, **_BASE),
              impl="cascade", tile="on", block_edges=5, drain=False)


# ---------------------------------------------------------------------------
# differentials: the full arm sweep (slow)


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["cascade", "wave"])
@pytest.mark.parametrize("arm", ["base", "supervised", "daemon", "traced"])
def test_tiled_matches_split_full_sweep(arm, impl):
    cfg = {"base": SimConfig(**_BASE),
           "supervised": SimConfig(snapshot_timeout=5, snapshot_retries=2,
                                   **_BASE),
           "daemon": SimConfig(snapshot_every=6, **_BASE),
           "traced": SimConfig(trace_capacity=64, **_BASE)}[arm]
    _diff_arm(cfg, impl=impl, tile="on", trace=(arm == "traced") or None)


@pytest.mark.slow
def test_tiled_single_block_degenerate():
    # block_edges >= E: RNB=1, the DMA schedule's prologue/epilogue
    # collapse onto the same block
    _diff_arm(SimConfig(**_BASE), tile="on", block_edges=64)


@pytest.mark.slow
def test_tiled_matches_split_with_faults():
    _diff_arm(SimConfig(**_BASE), tile="on",
              faults=JaxFaults(seed=3, drop_rate=0.25, dup_rate=0.25))


@pytest.mark.slow
def test_tiled_matches_split_faults_and_supervisor_wave():
    _diff_arm(SimConfig(snapshot_timeout=5, snapshot_retries=2, **_BASE),
              impl="wave", tile="on",
              faults=JaxFaults(seed=3, drop_rate=0.25, dup_rate=0.25))


@pytest.mark.slow
def test_tiled_auto_engages_past_vmem_budget():
    """The acceptance shape: a ring set 2*E*C*4 = 16.8 MB over the 12 MB
    budget. fused_tick='auto' used to refuse it outright; now auto
    resolves (fused=on, tile=on) and stays bit-identical to the split
    path."""
    spec = ring_topology(256, tokens=512)
    topo = DenseTopology(spec)
    cfg = SimConfig(max_snapshots=2, queue_capacity=8192, max_recorded=16)
    delay = HashJaxDelay(seed=7)

    def mk(fused):
        return TickKernel(topo, cfg, delay, exact_impl="cascade",
                          megatick=2, queue_engine="auto",
                          kernel_engine="pallas", fused_tick=fused,
                          fused_block_edges=64)

    split, fused = mk("off"), mk("auto")
    assert fused.fused == "on", fused.fused_reason
    assert fused.fused_tile == "on", fused.fused_tile_reason
    s = init_state(topo, cfg, delay.init_state())
    for e in range(0, topo.e, 31):
        s = split.inject_send(s, np.int32(e), np.int32(2))
    s = split.inject_snapshot(s, np.int32(0))
    s = jax.device_get(s)
    _assert_identical(fused.run_ticks(s, np.int32(4)),
                      split.run_ticks(s, np.int32(4)))


# ---------------------------------------------------------------------------
# the fused serve + stream arms


def test_serve_report_stamps_fused_fields():
    """Cheap tier-1 plumbing check: every serve report carries the
    fused_tick/fused_tile/fused_emulated stamps (bench satellites read
    them into the JSON rows)."""
    from chandy_lamport_tpu.models.workloads import serve_workload
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.serving.server import serve_run
    spec = ring_topology(6, tokens=12)
    cfg = SimConfig.for_workload(snapshots=2, max_recorded=16)
    runner = BatchedRunner(spec, cfg, HashJaxDelay(seed=7), 2,
                           scheduler="sync")
    reqs = serve_workload(spec, 4, seed=17, rate=2.0, tenants=2,
                          max_phases=4)
    _, _, report = serve_run(runner, reqs, policy="edf", stretch=2,
                             drain_chunk=8)
    assert report["fused_tick"] == "off"
    assert report["fused_tile"] == "off"
    assert report["fused_emulated"] is False


@pytest.mark.slow
def test_serve_fused_tiled_matches_split():
    """The fused serve step (acceptance): one seeded serve schedule
    driven through fused-resident and fused-tiled kernels must produce
    byte-identical results to the split path, and the report must stamp
    fused_emulated=True off-TPU."""
    from chandy_lamport_tpu.models.workloads import serve_workload
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.serving.server import serve_run
    spec = ring_topology(8, tokens=16)
    cfg = SimConfig.for_workload(snapshots=2, max_recorded=32)
    reqs = serve_workload(spec, 6, seed=17, rate=2.0, tenants=2,
                          max_phases=6)

    def drive(fused, tile):
        runner = BatchedRunner(spec, cfg, HashJaxDelay(seed=7), 2,
                               scheduler="exact", megatick=2,
                               kernel_engine="pallas", fused_tick=fused,
                               fused_tile=tile)
        _, stream, report = serve_run(runner, reqs, policy="edf",
                                      stretch=2, drain_chunk=8)
        return runner.stream_results(stream), report

    ref, _ = drive("off", "off")
    for tile in ("off", "on"):
        rows, report = drive("on", tile)
        assert report["fused_tick"] == "on"
        assert report["fused_tile"] == tile
        assert report["fused_emulated"] is True
        assert rows == ref, f"tile={tile}"


@pytest.mark.slow
def test_stream_fused_tiled_matches_split():
    """The stream engine's chunked drain through the fused kernel
    (_fused_stream_drain), resident and tiled, against the split
    scanned-cond-tick drain: identical stream state."""
    import jax.numpy as jnp
    from chandy_lamport_tpu.models.workloads import stream_jobs
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    spec = ring_topology(8, tokens=16)
    cfg = SimConfig.for_workload(snapshots=2, max_recorded=32)
    jobs = stream_jobs(spec, 6, seed=5, base_phases=2, max_phases=4)

    def drive(fused, tile):
        runner = BatchedRunner(spec, cfg, HashJaxDelay(seed=7), 2,
                               scheduler="exact", megatick=2,
                               kernel_engine="pallas", fused_tick=fused,
                               fused_tile=tile)
        pool = runner.pack_jobs(jobs)
        _, stream = runner.run_stream(pool, stretch=2, drain_chunk=8)
        return jax.device_get(stream)

    ref = drive("off", "off")
    for tile in ("off", "on"):
        got = drive("on", tile)
        for f in ref._fields:
            va, vg = getattr(ref, f), getattr(got, f)
            if isinstance(va, (np.ndarray, jnp.ndarray)):
                assert np.array_equal(np.asarray(va), np.asarray(vg)), \
                    (tile, f)
