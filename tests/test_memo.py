"""Memo plane (content-addressed admission + fast-forwarding): bit-exactness.

The contract under test (parallel/batch docstring, utils/memocache): with
``memo != 'off'`` every job's served summary — whether executed, coalesced
onto a duplicate's lane, or read back from the persistent cache — is
BIT-IDENTICAL to the row the same pool produces under ``memo='off'``. The
oracle is therefore the memo-off run of the SAME content-keyed pool (the
pool, not the job list: index-keyed pools give byte-identical scripts
distinct fault/delay streams, so the A/B must share one pack).

Tier-1 keeps one tiny ring-8 pool with a Zipf duplicate mix and shares
module-scoped runners so each jitted stream step compiles once; the
fast-forward check uses the 2-node one-link livelock (a snapshot on the
sink can never complete, so the drain grinds to ERR_TICK_LIMIT through
thousands of pure +1 ticks — exactly what memo='full' jumps). The deep
fault-armed sweep over both schedulers is ``slow``.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys

import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import SnapshotEvent
from chandy_lamport_tpu.models.faults import JaxFaults
from chandy_lamport_tpu.models.workloads import ring_topology, stream_jobs
from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.fixtures import TopologySpec
from chandy_lamport_tpu.utils.memocache import (
    MEMOCACHE_SCHEMA_VERSION,
    MemoCacheError,
    SummaryCache,
)

TOPO = ring_topology(8)
CFG = SimConfig.for_workload(snapshots=4, max_recorded=128)
J, B = 10, 4
NUNIQ = 4  # J=10 at dup_rate 0.6 -> a 4-scenario library + 6 repeats


def _delay():
    return make_fast_delay("hash", 11)


def _jobs():
    return stream_jobs(TOPO, J, seed=5, base_phases=3, max_phases=12,
                       dup_rate=0.6)


def _strip(rows):
    """Drop the admission- and provenance-dependent keys: everything left
    must be bit-identical between the memo arms and the off oracle."""
    return [{k: v for k, v in r.items()
             if k not in ("admit_step", "digest", "served_from")}
            for r in rows]


@pytest.fixture(scope="module")
def off_runner(ring8_sync_stream_runner):
    # the session-scoped shared instance (conftest): same (TOPO, CFG,
    # delay, B) shape as declared above — the memo-off oracle rides the
    # stream step test_stream.py already compiled
    return ring8_sync_stream_runner


@pytest.fixture(scope="module")
def pool(off_runner):
    # ONE content-keyed pool shared by every arm — the memo plane requires
    # content keys, and the off oracle must run the identical operands
    return off_runner.pack_jobs(_jobs(), content_keys=True)


@pytest.fixture(scope="module")
def off_rows(off_runner, pool):
    _, stream = off_runner.run_stream(pool, stretch=3, drain_chunk=16)
    return off_runner.stream_results(stream)


@pytest.fixture(scope="module")
def admit_runner(tmp_path_factory):
    cache = str(tmp_path_factory.mktemp("memo") / "summaries.jsonl")
    return BatchedRunner(TOPO, CFG, _delay(), B, scheduler="sync",
                         memo="admit", memo_cache=cache)


def test_duplicate_jobs_share_digests(pool):
    digests = {bytes(pool.digest[j].tobytes()) for j in range(J)}
    assert len(digests) == NUNIQ
    assert all(d != b"\x00" * 32 for d in digests)


def test_digest_changes_with_execution_identity(off_runner, pool):
    # a different scheduler is a different computation: nothing may alias
    exact = BatchedRunner(TOPO, CFG, _delay(), B, scheduler="exact")
    pool2 = exact.pack_jobs(_jobs(), content_keys=True)
    assert not np.array_equal(np.asarray(pool.digest),
                              np.asarray(pool2.digest))
    # and a different job mix yields different addresses
    other = off_runner.pack_jobs(
        stream_jobs(TOPO, J, seed=6, base_phases=3, max_phases=12,
                    dup_rate=0.6), content_keys=True)
    assert not np.array_equal(np.asarray(pool.digest),
                              np.asarray(other.digest))


def test_digest_stable_across_processes(pool):
    # the cache is only sound if the address survives a process boundary
    code = (
        "from chandy_lamport_tpu.config import SimConfig\n"
        "from chandy_lamport_tpu.models.workloads import ring_topology, "
        "stream_jobs\n"
        "from chandy_lamport_tpu.ops.delay_jax import make_fast_delay\n"
        "from chandy_lamport_tpu.parallel.batch import BatchedRunner\n"
        "r = BatchedRunner(ring_topology(8), "
        "SimConfig.for_workload(snapshots=4, max_recorded=128), "
        "make_fast_delay('hash', 11), 4, scheduler='sync')\n"
        "jobs = stream_jobs(ring_topology(8), 10, seed=5, base_phases=3, "
        "max_phases=12, dup_rate=0.6)\n"
        "p = r.pack_jobs(jobs, content_keys=True)\n"
        "print(bytes(p.digest[0].tobytes()).hex())\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "JAX_ENABLE_X64": "True"}
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip().splitlines()[-1] == \
        bytes(pool.digest[0].tobytes()).hex()


@pytest.fixture(scope="module")
def coalesced_run(admit_runner, pool):
    """First (cold-cache) admit run: coalesces duplicates and flushes the
    persistent cache — the warm-cache test reads the file it leaves."""
    _, stream = admit_runner.run_stream(pool, stretch=3, drain_chunk=16)
    return (admit_runner.stream_results(stream),
            admit_runner.summarize_stream(stream))


def test_coalesced_rows_bit_identical_to_off(coalesced_run, off_rows):
    rows, summ = coalesced_run
    assert _strip(rows) == _strip(off_rows)
    assert summ["coalesced_jobs"] == J - NUNIQ
    assert summ["cache_hits"] == 0  # cold cache: nothing served from file
    assert summ["shadow_checks"] >= 1
    served = [r for r in rows if r.get("served_from")]
    assert len(served) == J - NUNIQ
    assert all(r["served_from"] == "coalesce" and r["admit_step"] == -1
               and len(r["digest"]) == 64 for r in served)


@pytest.mark.slow  # ~10 s; cross-run warm serving stays tier-1 via the
# serving-layer warm-summary-at-ingest test
def test_warm_cache_serves_across_runs(admit_runner, pool, off_rows,
                                       coalesced_run):
    # the cold run flushed the cache file; a second run of the same
    # pool must serve every job either from file or as the shadow audit
    assert os.path.exists(admit_runner.memo_cache_path)
    _, stream = admit_runner.run_stream(pool, stretch=3, drain_chunk=16)
    rows = admit_runner.stream_results(stream)
    assert _strip(rows) == _strip(off_rows)
    summ = admit_runner.summarize_stream(stream)
    assert summ["cache_hits"] > 0
    assert summ["cache_hits"] + summ["coalesced_jobs"] \
        + summ["shadow_checks"] >= J


def test_kill_and_resume_replans_identically(admit_runner, pool, off_rows,
                                             tmp_path):
    # a killed memo run resumes bit-exactly: the admission plan is a pure
    # function of (pool, cache file) and the cache only flushes at run END,
    # so the resumed process re-derives the same plan, finishes the
    # executed jobs, and serves the same summaries
    old_cache = admit_runner.memo_cache_path
    admit_runner.memo_cache_path = str(tmp_path / "cold.jsonl")
    try:
        ckpt = str(tmp_path / "memo_stream.npz")
        _, killed = admit_runner.run_stream(pool, stretch=3, drain_chunk=16,
                                            checkpoint=ckpt,
                                            checkpoint_every=2,
                                            kill_after_saves=2)
        assert int(killed.jobs_done) < NUNIQ + 1, \
            "kill landed after the queue drained — shrink checkpoint_every"
        from chandy_lamport_tpu.utils.checkpoint import load_state
        like = (admit_runner.init_batch(), admit_runner.init_stream(pool))
        (state, stream), _meta = load_state(ckpt, like)
        _, stream = admit_runner.run_stream(pool, stretch=3, drain_chunk=16,
                                            state=state, stream=stream)
        assert _strip(admit_runner.stream_results(stream)) \
            == _strip(off_rows)
    finally:
        admit_runner.memo_cache_path = old_cache


@pytest.mark.slow  # memo=full FF also exercised by the staticcheck runtime plane
def test_fast_forward_skips_livelocked_drain():
    # two nodes, ONE link a->b: a snapshot initiated at the sink can never
    # reach "a", so the drain runs pure +1 ticks to ERR_TICK_LIMIT — the
    # exact recurrence memo='full' detects and jumps in one step
    spec = TopologySpec(nodes=[("a", 10), ("b", 10)], links=[("a", "b")])
    cfg = dataclasses.replace(
        SimConfig.for_workload(snapshots=2, max_recorded=32), max_ticks=600)
    jobs = [[SnapshotEvent("b")]] * 3
    r_off = BatchedRunner(spec, cfg, _delay(), 2, scheduler="exact")
    pool = r_off.pack_jobs(jobs, content_keys=True)
    _, s_off = r_off.run_stream(pool, stretch=2, drain_chunk=16)
    r_full = BatchedRunner(spec, cfg, _delay(), 2, scheduler="exact",
                           memo="full")
    _, s_full = r_full.run_stream(pool, stretch=2, drain_chunk=16)
    assert _strip(r_full.stream_results(s_full)) \
        == _strip(r_off.stream_results(s_off))
    summ = r_full.summarize_stream(s_full)
    assert summ["ff_skipped_ticks"] > 0
    # the jump replaces drain slices wholesale, never adds steps
    assert int(s_full.steps) < int(s_off.steps)


def _summ(t):
    # a minimal but realistic summary row (plain JSON scalars/lists)
    return {"time": int(t), "error": 0, "tokens": [t, t + 1],
            "snapshots_started": 1}


def test_cache_lru_evicts_oldest_and_counts(tmp_path):
    c = SummaryCache(None, max_entries=2)
    d = ["a" * 64, "b" * 64, "c" * 64]
    c.put(d[0], _summ(0))
    c.put(d[1], _summ(1))
    # a get refreshes recency: d[0] becomes most-recent, so d[1] is the
    # LRU victim when d[2] crosses the entry cap
    assert c.get(d[0]) is not None
    c.put(d[2], _summ(2))
    assert c.get(d[1]) is None
    assert c.get(d[0]) is not None and c.get(d[2]) is not None
    assert c.evictions == 1 and c.evicted_bytes > 0


def test_cache_max_bytes_bounds_flushed_file(tmp_path):
    path = str(tmp_path / "bounded.jsonl")
    line = SummaryCache._line_bytes("a" * 64, _summ(0))
    c = SummaryCache(path, max_bytes=2 * line + 1)
    for i, d in enumerate(("a" * 64, "b" * 64, "c" * 64)):
        c.put(d, _summ(0))  # equal-size lines -> capacity is exactly 2
    assert c.evictions == 1 and c.evicted_bytes == line
    c.flush()
    assert os.path.getsize(path) <= 2 * line + 1
    # recency survives the restart: the survivors are the two newest
    c2 = SummaryCache(path, max_bytes=2 * line + 1)
    assert c2.get("a" * 64) is None
    assert c2.get("b" * 64) is not None and c2.get("c" * 64) is not None


def test_cache_reload_evicts_under_tightened_bounds(tmp_path):
    # an unbounded run's file reopened with a cap evicts at LOAD time,
    # oldest-written first (flush persists in recency order)
    path = str(tmp_path / "tight.jsonl")
    c = SummaryCache(path)
    for ch in "abcd":
        c.put(ch * 64, _summ(ord(ch)))
    c.flush()
    c2 = SummaryCache(path, max_entries=2)
    assert c2.evictions == 2
    assert c2.get("a" * 64) is None and c2.get("b" * 64) is None
    assert c2.get("c" * 64) is not None and c2.get("d" * 64) is not None


def test_cache_rejects_negative_bounds(tmp_path):
    with pytest.raises(ValueError, match=">= 0"):
        SummaryCache(None, max_entries=-1)
    with pytest.raises(ValueError, match=">= 0"):
        SummaryCache(None, max_bytes=-1)


@pytest.mark.slow  # ~7 s; per-key eviction is also pinned by the serving exec-cache tests
def test_runner_surfaces_eviction_counters(tmp_path, pool, off_rows):
    # a bounded runner reports its cache evictions through the memo books
    cache = str(tmp_path / "tiny.jsonl")
    r = BatchedRunner(TOPO, CFG, _delay(), B, scheduler="sync",
                      memo="admit", memo_cache=cache,
                      memo_cache_entries=2)
    _, stream = r.run_stream(pool, stretch=3, drain_chunk=16)
    assert _strip(r.stream_results(stream)) == _strip(off_rows)
    summ = r.summarize_stream(stream)
    # NUNIQ=4 distinct digests through a 2-entry cache: at least two
    # insertions must have pushed out an older entry
    assert summ["cache_evictions"] >= 2
    assert summ["cache_evicted_bytes"] > 0
    with open(cache) as f:
        assert len(f.readlines()) <= 2


@pytest.mark.parametrize("poison, excerpt", [
    ("{not json", "not valid JSON"),
    ('{"digest": "ab", "summary": {}}\n', "missing the"),
    ('{"schema": 99, "digest": "%s", "summary": {}}\n' % ("a" * 64),
     "schema version 99"),
    ('{"schema": %d, "digest": "zz", "summary": {}}\n'
     % MEMOCACHE_SCHEMA_VERSION, "not a sha256 hex string"),
    ('{"schema": %d, "digest": "%s", "summary": 7}\n'
     % (MEMOCACHE_SCHEMA_VERSION, "b" * 64), "summary is not an"),
])
def test_damaged_cache_is_rejected_loudly(tmp_path, poison, excerpt):
    path = tmp_path / "cache.jsonl"
    path.write_text(poison)
    with pytest.raises(MemoCacheError, match=excerpt):
        SummaryCache(str(path))


def test_runner_refuses_damaged_cache(admit_runner, pool, tmp_path):
    # the rejection reaches the runner: a poisoned file fails the run
    # up front instead of silently serving stale or garbled summaries
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"schema": 99, "digest": "%s", "summary": {}}\n'
                   % ("c" * 64))
    old_cache = admit_runner.memo_cache_path
    admit_runner.memo_cache_path = str(bad)
    try:
        with pytest.raises(MemoCacheError, match="schema version 99"):
            admit_runner.run_stream(pool, stretch=3, drain_chunk=16)
    finally:
        admit_runner.memo_cache_path = old_cache


def test_memo_requires_content_keyed_pool(admit_runner):
    # an index-keyed pool has no digests; admitting it under memo would
    # coalesce jobs that run DIFFERENT fault/delay streams
    off = BatchedRunner(TOPO, CFG, _delay(), B, scheduler="sync")
    plain = off.pack_jobs(_jobs(), content_keys=False)
    with pytest.raises(ValueError, match="content-addressed"):
        admit_runner.run_stream(plain, stretch=3, drain_chunk=16)


@pytest.mark.slow
@pytest.mark.parametrize("sched", ["exact", "sync"])
def test_memo_full_deep_sweep_with_faults(sched, tmp_path):
    # the acceptance sweep: heavy-tailed duplicate mix with the fault
    # adversary armed on every third job, memo='full' vs 'off' on the
    # shared content-keyed pool — every served row bit-identical
    jcount, slots = 24, 8
    faults = JaxFaults(7, drop_rate=0.05, dup_rate=0.05,
                       max_delay=_delay().max_delay)
    jobs = stream_jobs(TOPO, jcount, seed=6, base_phases=3, max_phases=16,
                       dup_rate=0.5)
    armed = np.arange(jcount) % 3 == 0
    r_off = BatchedRunner(TOPO, CFG, _delay(), slots, scheduler=sched,
                          faults=faults, quarantine=True)
    pool = r_off.pack_jobs(jobs, fault_armed=armed, content_keys=True)
    _, s_off = r_off.run_stream(pool, stretch=4, drain_chunk=16)
    r_memo = BatchedRunner(TOPO, CFG, _delay(), slots, scheduler=sched,
                           faults=faults, quarantine=True, memo="full",
                           memo_cache=str(tmp_path / f"{sched}.jsonl"))
    _, s_memo = r_memo.run_stream(pool, stretch=4, drain_chunk=16)
    assert _strip(r_memo.stream_results(s_memo)) \
        == _strip(r_off.stream_results(s_off))
    summ = r_memo.summarize_stream(s_memo)
    assert summ["coalesced_jobs"] > 0


def test_cache_concurrent_flushes_merge_not_clobber(tmp_path):
    # two caches over ONE path, loaded before either wrote: the second
    # flush must fold the first writer's entries back in under the file
    # lock (utils/filelock) instead of rewriting the file from its own
    # stale view — the cross-process merge semantics, in-process
    path = str(tmp_path / "shared.jsonl")
    a = SummaryCache(path)
    b = SummaryCache(path)
    a.put("a" * 64, _summ(1))
    a.flush()
    b.put("b" * 64, _summ(2))
    b.flush()
    merged = SummaryCache(path)
    assert merged.get("a" * 64) == _summ(1)
    assert merged.get("b" * 64) == _summ(2)
    # disk entries fold in as OLDER than the writer's own: under a
    # 1-entry cap the other process's entry is the eviction victim
    tight = SummaryCache(path, max_entries=1)
    assert len(tight) == 1


_WRITER = """
import hashlib, sys
from chandy_lamport_tpu.utils.memocache import SummaryCache
path, tag = sys.argv[1], sys.argv[2]
for i in range(8):
    c = SummaryCache(path) if __import__('os').path.exists(path) \\
        else SummaryCache(path)
    d = hashlib.sha256(f"{tag}-{i}".encode()).hexdigest()
    c.put(d, {"tag": tag, "i": i})
    c.flush()
"""


def test_cache_cross_process_writers_all_survive(tmp_path):
    # the real thing: two processes hammer one cache path with
    # interleaved load/put/flush cycles; the fcntl lock serializes the
    # read-merge-write so every digest from both writers survives
    import hashlib

    path = str(tmp_path / "mp.jsonl")
    procs = [subprocess.Popen([sys.executable, "-c", _WRITER, path, tag],
                              cwd=os.path.dirname(os.path.dirname(
                                  os.path.abspath(__file__))))
             for tag in ("a", "b")]
    for p in procs:
        assert p.wait(timeout=120) == 0
    final = SummaryCache(path)
    for tag in ("a", "b"):
        for i in range(8):
            d = hashlib.sha256(f"{tag}-{i}".encode()).hexdigest()
            assert final.get(d) == {"tag": tag, "i": i}, (tag, i)
