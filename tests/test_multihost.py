"""Multi-host helpers: single-process no-op init + hybrid mesh shapes on the
virtual 8-device CPU mesh (real DCN needs multiple hosts; the mesh/axes
logic is what's testable here and what the driver's dryrun exercises)."""

import jax
import pytest

from chandy_lamport_tpu.parallel import multihost


def test_initialize_is_noop_without_coordinator(monkeypatch):
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert multihost.initialize() is False


def test_hybrid_mesh_axes():
    mesh = multihost.hybrid_mesh(graph=2)
    assert mesh.shape["graph"] == 2
    assert mesh.shape["data"] == len(jax.devices()) // 2
    assert tuple(mesh.axis_names) == ("data", "graph")


def test_hybrid_mesh_rejects_bad_split():
    with pytest.raises(ValueError):
        multihost.hybrid_mesh(graph=3)  # does not divide 8


def test_process_info_single_process():
    info = multihost.process_info()
    assert info["process_count"] == 1
    assert info["local_devices"] == info["global_devices"] == len(jax.devices())
