"""Execution coverage for multihost.initialize (round-2 VERDICT item 8).

The in-suite tests of parallel/multihost.py exercise only the
single-process no-op path; this runs the real thing — two local processes,
loopback coordinator, Gloo-connected CPU collectives — via
tools/multihost_dryrun.py (which the driver can also run standalone).
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# some jax builds ship an XLA:CPU without cross-process collectives; that is
# an environment limit, not a regression — skip (keeping the signal for real
# multi-host runs) instead of failing tier-1 forever on such images
_CPU_LIMIT = "Multiprocess computations aren't implemented on the CPU backend"


def test_two_process_loopback_dryrun():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "multihost_dryrun.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=280)
    blob = (proc.stdout + proc.stderr).decode(errors="replace")
    if proc.returncode != 0 and _CPU_LIMIT in blob:
        pytest.skip(f"env limit: {_CPU_LIMIT}")
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    verdict = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert verdict["ok"] is True
    # both workers completed their cross-process-aggregated storms, and
    # both ran the sparse halo exchange over the fabric (graph-only +
    # dp x graph) with finals bit-identical to the dense engine
    assert len(verdict["workers"]) == 2
    for w in verdict["workers"]:
        row = json.loads(w.splitlines()[-1])
        assert row["global_snapshots_completed"] == 8
        assert row["graph_engines_agree"] is True
        model = row["comm_bytes_model"]
        assert model["sparse_bytes_per_tick"] > 0
        assert model["dense_bytes_per_tick"] > model["sparse_bytes_per_tick"]
