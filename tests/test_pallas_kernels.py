"""Differential coverage for the Pallas tick-kernel engine (PR 9).

The pallas engine (SimConfig.kernel_engine, chandy_lamport_tpu/kernels)
routes the ring-queue head/select/pop/append chain and the edge->node
segment reductions through hand-fused Pallas kernels; "xla" is the stock
formulation, kept as the oracle. The two must be BIT-IDENTICAL — same
ring planes, same error bits, same sampler stream — on every exact
formulation (fold, cascade, wave), under the sync scheduler, composed
with faults/supervisor/tracing, on the graph-sharded runner, and on the
reference goldens. Off-TPU the kernels run as interpret-mode emulation
(kernels.pallas_interpret), so these tests exercise the exact kernel
BODIES on the CPU mesh.
"""

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import DenseTopology, init_state
from chandy_lamport_tpu.kernels import resolve_kernel_engine
from chandy_lamport_tpu.models.workloads import (
    erdos_renyi,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, HashJaxDelay
from chandy_lamport_tpu.ops.tick import TickKernel
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.compare import dense_state_mismatches
from tests.test_queue_engine import _craft

IMPLS = ("fold", "cascade", "wave")
ENGINES = ("xla", "pallas")


def _kernel_pair(impl, cfg, spec=None, delay=None, faults=None):
    topo = DenseTopology(spec or erdos_renyi(8, 2.5, seed=7, tokens=50))
    delay = delay or FixedJaxDelay(2)
    return topo, delay, [
        TickKernel(topo, cfg, delay, marker_mode="ring", exact_impl=impl,
                   kernel_engine=eng, faults=faults) for eng in ENGINES]


# tier-1 wall budget is nearly exhausted by the seed suite (846 s of the
# 870 s window before this file existed), so tier-1 keeps only the
# cascade legs — the kernels are formulation-independent (fold/wave call
# the same primitives) and the fold/wave legs ride the slow lane
@pytest.mark.parametrize("impl", [
    pytest.param("fold", marks=pytest.mark.slow), "cascade",
    pytest.param("wave", marks=pytest.mark.slow)])
@pytest.mark.parametrize("case", ["wrap", "full", "marker_head"])
def test_crafted_ring_regimes(impl, case):
    """The three ring regimes that distinguish queue addressings —
    wraparound, full capacity, marker at head — bit-identical between the
    fused queue_step/append kernels and the stock path."""
    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    topo, delay, kernels = _kernel_pair(impl, cfg)
    finals = []
    for k in kernels:
        s = _craft(init_state(topo, cfg, delay.init_state()), topo, cfg,
                   case)
        s = k.tick(s)            # fused select/pop (+ routed appends)
        s = k.tick(s)            # second tick: pops across the wrap point
        finals.append(jax.device_get(s))
    assert dense_state_mismatches(*finals) == []
    if case == "full" and impl != "fold":
        # popped-up-front semantics: a full ring with no same-tick append
        # must NOT flag overflow under either engine
        assert int(finals[0].error) == 0


@pytest.mark.parametrize("impl", IMPLS)
def test_append_rows_partial_active(impl):
    """The fused append kernel directly: a partially-active row on a
    wrapped ring must land the same slots, lengths, and overflow bits as
    the stock scatter (inactive rows must drop, not write)."""
    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    topo, delay, kernels = _kernel_pair(impl, cfg)
    active = np.arange(topo.e) % 2 == 0
    rt = np.full(topo.e, 9, np.int32)
    data = np.arange(topo.e, dtype=np.int32) + 100
    outs = []
    for k in kernels:
        s = _craft(init_state(topo, cfg, delay.init_state()), topo, cfg,
                   "wrap")
        outs.append(jax.device_get(
            jax.jit(k._append_rows)(s, active, rt, False, data)))
    assert dense_state_mismatches(*outs) == []
    np.testing.assert_array_equal(outs[0].q_len[active], 3)
    np.testing.assert_array_equal(outs[0].q_len[~active], 2)


@pytest.mark.parametrize("impl", IMPLS)
def test_append_rows_overflow_parity(impl):
    """Appending onto a FULL ring flags ERR_QUEUE_OVERFLOW identically
    (and clobbers the same slot) under both engines — the kernel's
    error-bit reduction matches the stock formulation."""
    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    topo, delay, kernels = _kernel_pair(impl, cfg)
    active = np.ones(topo.e, bool)
    outs = []
    for k in kernels:
        s = _craft(init_state(topo, cfg, delay.init_state()), topo, cfg,
                   "full")
        outs.append(jax.device_get(jax.jit(k._append_rows)(
            s, active, np.full(topo.e, 9, np.int32), False,
            np.int32(1))))
    assert dense_state_mismatches(*outs) == []
    assert int(outs[0].error) != 0


@pytest.mark.slow
@pytest.mark.parametrize("impl", IMPLS)
def test_storm_xla_vs_pallas(impl):
    """End-to-end batched storms: the full protocol (injections, marker
    broadcasts, segment-reduced credits, drain) bit-identical across
    kernel engines, per exact formulation."""
    spec = erdos_renyi(16, 2.5, seed=11, tokens=60)
    cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48)
    finals = []
    for eng in ENGINES:
        r = BatchedRunner(spec, cfg, HashJaxDelay(seed=31), batch=4,
                          scheduler="exact", exact_impl=impl,
                          kernel_engine=eng)
        prog = storm_program(
            r.topo, phases=5, amount=2,
            snapshot_phases=staggered_snapshots(r.topo, 3))
        finals.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    assert int(np.max(finals[0].error)) == 0
    assert dense_state_mismatches(*finals) == []


@pytest.mark.slow
def test_sync_scheduler_xla_vs_pallas():
    """The split-representation sync tick routes its head reads, appends
    and marker/credit segment reductions through the same engine-selected
    primitives — pin it too."""
    spec = erdos_renyi(16, 2.5, seed=13, tokens=60)
    cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48)
    finals = []
    for eng in ENGINES:
        r = BatchedRunner(spec, cfg, HashJaxDelay(seed=37), batch=4,
                          scheduler="sync", kernel_engine=eng)
        prog = storm_program(
            r.topo, phases=5, amount=2,
            snapshot_phases=staggered_snapshots(r.topo, 3))
        finals.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    assert int(np.max(finals[0].error)) == 0
    assert dense_state_mismatches(*finals) == []


@pytest.mark.parametrize("impl", [
    "cascade", pytest.param("wave", marks=pytest.mark.slow)])
def test_fault_path_split_parity(impl):
    """With faults armed the fused queue step splits (pallas head read,
    XLA fault gates, pallas select_pop) — tick-level parity on the
    crafted wrap regime under an aggressive adversary, cheap enough for
    tier-1 (the full faults+supervisor+trace storm rides the slow lane
    below)."""
    from chandy_lamport_tpu.models.faults import JaxFaults

    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    topo, delay, kernels = _kernel_pair(
        impl, cfg, faults=JaxFaults(7, drop_rate=0.3, dup_rate=0.2,
                                    jitter_rate=0.2))
    finals = []
    for k in kernels:
        s = _craft(init_state(topo, cfg, delay.init_state()), topo, cfg,
                   "wrap")
        s = k.tick(s)
        s = k.tick(s)
        finals.append(jax.device_get(s))
    assert dense_state_mismatches(*finals) == []


@pytest.mark.slow
def test_composes_with_faults_supervisor_trace():
    """The adversary path splits the fused queue step (pallas head read,
    XLA fault gates, pallas select_pop) — so faults + supervisor + flight
    recorder together must stay bit-identical across engines, including
    the trace ring contents and the supervisor's retry bookkeeping."""
    import dataclasses

    from chandy_lamport_tpu.models.faults import JaxFaults
    from chandy_lamport_tpu.utils.tracing import JaxTrace

    spec = erdos_renyi(8, 2.5, seed=17, tokens=60)
    cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48,
                    snapshot_timeout=16, snapshot_retries=2)
    finals = []
    for eng in ENGINES:
        r = BatchedRunner(
            spec, dataclasses.replace(cfg), HashJaxDelay(seed=41), batch=2,
            scheduler="exact", exact_impl="cascade", kernel_engine=eng,
            faults=JaxFaults(7, drop_rate=0.05, dup_rate=0.05,
                             jitter_rate=0.05),
            trace=JaxTrace())
        prog = storm_program(
            r.topo, phases=3, amount=2,
            snapshot_phases=staggered_snapshots(r.topo, 2))
        finals.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    assert dense_state_mismatches(*finals) == []


@pytest.mark.slow
def test_megatick_xla_vs_pallas():
    """megatick>1 moves the tick loop inside a scan — the fused kernels
    must survive the scan-carried q planes bit-for-bit."""
    spec = erdos_renyi(16, 2.5, seed=19, tokens=60)
    cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48)
    finals = []
    for eng in ENGINES:
        r = BatchedRunner(spec, cfg, HashJaxDelay(seed=47), batch=2,
                          scheduler="exact", exact_impl="cascade",
                          megatick=2, kernel_engine=eng)
        prog = storm_program(
            r.topo, phases=5, amount=2,
            snapshot_phases=staggered_snapshots(r.topo, 2))
        finals.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    assert int(np.max(finals[0].error)) == 0
    assert dense_state_mismatches(*finals) == []


@pytest.mark.slow
def test_stream_xla_vs_pallas():
    """The streaming engine's harvest/admit cycle recycles lanes over the
    same tick kernels — per-job result rows must match across engines."""
    from chandy_lamport_tpu.models.workloads import ring_topology, stream_jobs

    topo_spec = ring_topology(8)
    cfg = SimConfig.for_workload(snapshots=4, max_recorded=128)
    jobs = stream_jobs(topo_spec, 6, seed=5, base_phases=3, max_phases=10)
    rows = []
    for eng in ENGINES:
        r = BatchedRunner(topo_spec, cfg, HashJaxDelay(seed=11), batch=3,
                          scheduler="sync", kernel_engine=eng)
        _, stream = r.run_stream(r.pack_jobs(jobs), stretch=3,
                                 drain_chunk=16)
        rows.append(r.stream_results(stream))
    assert rows[0] == rows[1]


@pytest.mark.slow
@pytest.mark.parametrize("comm_engine", ["dense", "sparse"])
def test_graphshard_xla_vs_pallas(comm_engine):
    """The graph-sharded runner's shard-local queue primitives route
    through the same kernels (queue-overflow bit gated off, the sharded
    twin's contract) — every state leaf bit-identical across engines."""
    from jax.sharding import Mesh

    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for the graph mesh")
    spec = erdos_renyi(16, 2.5, seed=11, tokens=80)
    cfg = SimConfig(queue_capacity=16, max_snapshots=8, max_recorded=16)
    mesh = Mesh(np.array(jax.devices()[:2]), ("graph",))
    r0 = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=1,
                       scheduler="sync")
    prog = storm_program(r0.topo, phases=8, amount=1,
                         snapshot_phases=staggered_snapshots(r0.topo, 3))
    finals = []
    for eng in ENGINES:
        gs = GraphShardedRunner(spec, cfg, mesh, fixed_delay=2,
                                comm_engine=comm_engine, kernel_engine=eng)
        assert gs.summarize(gs.init_state())["kernel_engine"] == eng
        finals.append(jax.device_get(gs.run_storm(
            gs.init_state(), np.asarray(prog.amounts),
            np.asarray(prog.snap))))
    a, b = finals
    for name in a._fields:
        np.testing.assert_array_equal(np.asarray(getattr(a, name)),
                                      np.asarray(getattr(b, name)),
                                      err_msg=name)


def test_auto_engine_resolution(caplog):
    """kernel_engine="auto" resolves to pallas only where compiled Pallas
    exists (TPU) and falls back to xla elsewhere WITH a logged reason —
    auto must never crash, and never silently select the interpret-mode
    emulation for a production run."""
    import logging

    assert resolve_kernel_engine("auto", backend="tpu") == "pallas"
    with caplog.at_level(logging.INFO, logger="chandy_lamport_tpu.kernels"):
        assert resolve_kernel_engine("auto", backend="cpu") == "xla"
    assert any("resolved to 'xla'" in rec.getMessage()
               for rec in caplog.records)
    # explicit engines pass through untouched, anywhere
    assert resolve_kernel_engine("pallas", backend="cpu") == "pallas"
    assert resolve_kernel_engine("xla", backend="tpu") == "xla"
    with pytest.raises(ValueError):
        resolve_kernel_engine("bogus")
    with pytest.raises(ValueError):
        SimConfig(kernel_engine="bogus")
    # a live runner under auto resolves and RUNS on this backend (the
    # never-crashes bar: CPU has no compiled Pallas, so auto -> xla)
    spec = erdos_renyi(8, 2.5, seed=7, tokens=50)
    cfg = SimConfig(max_snapshots=4, queue_capacity=16, max_recorded=16)
    r = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=2,
                      scheduler="sync", kernel_engine="auto")
    assert r.kernel_engine in ("xla", "pallas")
    if jax.default_backend() != "tpu":
        assert r.kernel_engine == "xla"
    prog = storm_program(r.topo, phases=3, amount=1,
                         snapshot_phases=staggered_snapshots(r.topo, 2))
    final = r.run_storm(r.init_batch(), prog)
    assert int(np.max(np.asarray(final.error))) == 0


def _run_golden(top, events, snaps, impl, engine):
    from chandy_lamport_tpu.api import run_events_file
    from chandy_lamport_tpu.utils.compare import (
        assert_snapshots_equal,
        check_tokens,
        sort_snapshots,
    )
    from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
    from chandy_lamport_tpu.utils.goldens import fixture_path

    actual, sim = run_events_file(
        fixture_path(top), fixture_path(events), backend="jax",
        config=SimConfig(kernel_engine=engine), exact_impl=impl)
    assert len(actual) == len(snaps)
    check_tokens(sim.node_tokens(), actual)
    expected = [read_snapshot_file(fixture_path(f)) for f in snaps]
    for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
        assert_snapshots_equal(e, a)


def test_golden_pallas_tier1():
    """One reference golden straight through the pallas engine (tier-1:
    the interpret-mode kernels reproduce the Go reference's snapshots
    bit-for-bit on a marker-rich fixture)."""
    from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS

    top, events, snaps = REFERENCE_TESTS[3]  # 3nodes-bidirectional
    _run_golden(top, events, snaps, "cascade", "pallas")


@pytest.mark.slow
def test_golden_sweep_all_pallas_cascade():
    """The full bit-identity bar, cascade leg: all 7 reference goldens
    through the pallas engine, each checked against the golden snapshot
    files (which the xla engine already matches — test_dense_golden — so
    golden equality IS xla equality)."""
    from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS

    for top, events, snaps in REFERENCE_TESTS:
        _run_golden(top, events, snaps, "cascade", "pallas")


@pytest.mark.slow
def test_golden_sweep_all_pallas_wave():
    """Wave leg of the sweep: the wave formulation refuses the goldens'
    order-dependent GoExactDelay sampler (it precomputes draws at their
    fold-order stream positions), so its bar is engine-vs-engine snapshot
    and token equality on every golden script under FixedJaxDelay."""
    from chandy_lamport_tpu.api import run_events_file
    from chandy_lamport_tpu.utils.compare import (
        assert_snapshots_equal,
        sort_snapshots,
    )
    from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path

    for top, events, _ in REFERENCE_TESTS:
        runs = []
        for eng in ENGINES:
            actual, sim = run_events_file(
                fixture_path(top), fixture_path(events), backend="jax",
                delay_model=FixedJaxDelay(2),
                config=SimConfig(kernel_engine=eng), exact_impl="wave")
            runs.append((sort_snapshots(actual), sim.node_tokens()))
        (snaps_x, tok_x), (snaps_p, tok_p) = runs
        assert tok_x == tok_p, events
        assert len(snaps_x) == len(snaps_p)
        for a, b in zip(snaps_x, snaps_p):
            assert_snapshots_equal(a, b)


@pytest.mark.slow
def test_golden_topologies_sync_storm_sweep():
    """The sync-scheduler leg of the sweep: the sync scheduler cannot
    replay event files (it is validated against SyncOracle, not the
    goldens), so its pallas bar is storm bit-identity on every golden
    TOPOLOGY instead."""
    from chandy_lamport_tpu.utils.fixtures import read_topology_file
    from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path

    tops = sorted({t[0] for t in REFERENCE_TESTS})
    for top in tops:
        spec = read_topology_file(fixture_path(top))
        cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48)
        finals = []
        for eng in ENGINES:
            r = BatchedRunner(spec, cfg, HashJaxDelay(seed=43), batch=2,
                              scheduler="sync", kernel_engine=eng)
            prog = storm_program(
                r.topo, phases=5, amount=2,
                snapshot_phases=staggered_snapshots(r.topo, 2))
            finals.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
        assert dense_state_mismatches(*finals) == [], top
