"""Interpret-mode validation of the Pallas record-append kernel
(ops/pallas_rec.py) against the jnp formulation it replaces.

Runs on the CPU mesh with interpret=True — the numerics and the
block-skip/aliasing semantics are what's validated here; device timing
happens on TPU via tools/profile_tick.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chandy_lamport_tpu.ops.pallas_rec import rec_append, rec_append_reference


def _case(seed, s=4, e=256, m=8, dtype=jnp.int16, density=0.05):
    rng = np.random.RandomState(seed)
    rec = jnp.asarray(rng.randint(0, 100, (s, m, e)), dtype)
    rec_len = jnp.asarray(rng.randint(0, m + 2, (s, e)), jnp.int32)
    mask = jnp.asarray(rng.rand(s, e) < density)
    amt = jnp.asarray(rng.randint(1, 1000, (e,)), jnp.int32)
    return rec, rec_len, mask, amt


@pytest.mark.parametrize("seed,dtype,density,e,tile_e", [
    (0, jnp.int16, 0.05, 256, 128),
    (1, jnp.int32, 0.3, 256, 128),
    (2, jnp.int16, 0.0, 256, 128),   # nothing dirty: every block skipped
    (3, jnp.int32, 1.0, 256, 128),   # everything dirty
    (4, jnp.int16, 0.2, 250, 128),   # ragged: 1 tile + 122-edge remainder
    (5, jnp.int32, 0.5, 65, 128),    # sub-lane E: pure jnp remainder path
    (6, jnp.int16, 0.3, 384, 256),   # full tile + 128-wide TAIL block
    (7, jnp.int32, 0.2, 700, 256),   # 2 full + tail 128 + 60-edge remainder
])
def test_matches_reference(seed, dtype, density, e, tile_e):
    rec, rec_len, mask, amt = _case(seed, e=e, dtype=dtype, density=density)
    want = rec_append_reference(rec, rec_len, mask, amt)
    got = rec_append(rec, rec_len, mask, amt, tile_e=tile_e, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_clean_blocks_preserved_via_aliasing():
    """A block with no dirty column must come through bit-identical — the
    aliased in-place semantics the skip relies on."""
    rec, rec_len, _, amt = _case(7, e=256)
    mask = jnp.zeros((rec.shape[0], rec.shape[-1]), bool).at[:, :128].set(
        jnp.asarray(np.random.RandomState(0).rand(rec.shape[0], 128) < 0.2))
    got = rec_append(rec.copy(), rec_len, mask, amt, tile_e=128,
                     interpret=True)
    # the second tile (edges 128..256) is untouched
    np.testing.assert_array_equal(np.asarray(got)[:, :, 128:],
                                  np.asarray(rec)[:, :, 128:])
    want = rec_append_reference(rec, rec_len, mask, amt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sync_scheduler_with_pallas_rec_matches_plain():
    """Full batched storm with SimConfig.use_pallas_rec=True (interpret
    mode on the CPU mesh) is bit-identical to the jnp rec path."""
    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import (
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    spec = scale_free(24, 2, seed=9, tokens=40)
    finals = []
    for flag in (False, True):
        cfg = SimConfig(queue_capacity=32, max_recorded=32,
                        use_pallas_rec=flag)
        runner = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=2,
                               scheduler="sync")
        prog = storm_program(runner.topo, phases=10, amount=1,
                             snapshot_phases=staggered_snapshots(
                                 runner.topo, 4, 1, 2, max_phases=10))
        finals.append(jax.device_get(
            runner.run_storm(runner.init_batch(), prog)))
    plain, pallas = finals
    assert int(np.asarray(plain.error).sum()) == 0
    for name in plain._fields:
        if name == "delay_state":
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(plain, name)),
            np.asarray(getattr(pallas, name)), err_msg=name)


def test_vmapped_batch_axis():
    """The bench path vmaps the tick over instances; the kernel must
    batch correctly (pallas_call's batching rule adds a grid dim)."""
    cases = [_case(10 + i, e=256) for i in range(3)]
    rec = jnp.stack([c[0] for c in cases])
    rec_len = jnp.stack([c[1] for c in cases])
    mask = jnp.stack([c[2] for c in cases])
    amt = jnp.stack([c[3] for c in cases])
    want = jax.vmap(rec_append_reference)(rec, rec_len, mask, amt)
    got = jax.vmap(lambda r, l, k, a: rec_append(
        r, l, k, a, tile_e=128, interpret=True))(rec, rec_len, mask, amt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
