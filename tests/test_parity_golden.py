"""Golden-file integration tests on the parity backend — the 7 reference
tests (snapshot_test.go:46-108) reproduced bit-exactly, plus the
token-conservation invariant (test_common.go:298-328)."""

import pytest

from chandy_lamport_tpu.api import run_events_file
from chandy_lamport_tpu.utils.compare import (
    assert_snapshots_equal,
    check_tokens,
    sort_snapshots,
)
from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path


@pytest.mark.parametrize("top,events,snaps", REFERENCE_TESTS,
                         ids=[t[1].removesuffix(".events") for t in REFERENCE_TESTS])
def test_golden_parity(top, events, snaps):
    actual, sim = run_events_file(fixture_path(top), fixture_path(events),
                                  backend="parity")
    assert len(actual) == len(snaps)
    check_tokens(sim.node_tokens(), actual)
    expected = [read_snapshot_file(fixture_path(f)) for f in snaps]
    for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
        assert_snapshots_equal(e, a)


def test_trace_mode_produces_epochs():
    _, sim = run_events_file(fixture_path("2nodes.top"),
                             fixture_path("2nodes-simple.events"),
                             backend="parity", trace=True)
    text = sim.trace.pretty()
    assert "startSnapshot(0)" in text
    assert "endSnapshot(0)" in text
    assert "marker(0)" in text
