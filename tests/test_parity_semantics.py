"""Semantic unit + property tests for the parity backend — coverage the
reference lacks (SURVEY.md §4.4): tick-rule unit tests with deterministic
delays, and token-conservation under randomized topologies/scripts."""

import numpy as np
import pytest

from chandy_lamport_tpu.core.parity import ParitySim, run_events
from chandy_lamport_tpu.core.spec import (
    Message,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.models.delay import FixedDelay, NumpyUniformDelay
from chandy_lamport_tpu.utils.compare import check_tokens


def make_sim(nodes, links, delay):
    sim = ParitySim(delay)
    for nid, tok in nodes:
        sim.add_node(nid, tok)
    for s, d in links:
        sim.add_link(s, d)
    return sim


def ring(n, tokens=10):
    ids = [f"N{i+1}" for i in range(n)]
    nodes = [(i, tokens) for i in ids]
    links = [(ids[i], ids[(i + 1) % n]) for i in range(n)]
    return nodes, links


def test_fifo_per_channel_and_delay():
    # delay=2: message sent at t=0 arrives exactly at t=2 (rt = now+delay).
    sim = make_sim(*ring(2), FixedDelay(2))
    sim.process_event(PassTokenEvent("N1", "N2", 3))
    sim.tick()
    assert sim.nodes["N2"].tokens == 10
    sim.tick()
    assert sim.nodes["N2"].tokens == 13
    assert sim.nodes["N1"].tokens == 7


def test_one_delivery_per_source_per_tick():
    # Two messages on the same channel, both eligible: only one delivered per
    # tick (sim.go:90 break), FIFO order.
    sim = make_sim(*ring(2), FixedDelay(1))
    sim.process_event(PassTokenEvent("N1", "N2", 1))
    sim.process_event(PassTokenEvent("N1", "N2", 2))
    sim.tick()
    assert sim.nodes["N2"].tokens == 11
    sim.tick()
    assert sim.nodes["N2"].tokens == 13


def test_head_of_line_blocking():
    # Head has rt=5, behind it rt would also be 5; nothing delivered earlier
    # even if a *later* message could theoretically arrive sooner: the head
    # blocks the channel (sim.go:82-84 peeks only the head).
    class Seq:
        def __init__(self, delays):
            self.delays = list(delays)

        def receive_time(self, now):
            return now + self.delays.pop(0)

    sim = make_sim(*ring(2), Seq([5, 1]))
    sim.process_event(PassTokenEvent("N1", "N2", 1))  # rt=5
    sim.process_event(PassTokenEvent("N1", "N2", 2))  # rt=1, stuck behind
    for _ in range(4):
        sim.tick()
    assert sim.nodes["N2"].tokens == 10
    sim.tick()  # t=5: head eligible
    assert sim.nodes["N2"].tokens == 11
    sim.tick()
    assert sim.nodes["N2"].tokens == 13


def test_sorted_source_order_n10_before_n2():
    # Lexicographic ordering: "N10" < "N2" (SURVEY §7.0 rule 1).
    assert sorted(["N2", "N10", "N1"]) == ["N1", "N10", "N2"]


def test_initiator_records_all_inbound_marker_case_excludes_src():
    sim = make_sim(*ring(3), FixedDelay(1))
    sim.start_snapshot("N1")
    snap = sim.nodes["N1"].active[0]
    assert snap.links_remaining == 1  # N1's only inbound is N3
    assert snap.recording == {"N3": True}
    sim.tick()  # marker N1->N2 delivered; N2 creates snapshot excluding N1
    snap2 = sim.nodes["N2"].active[0]
    assert snap2.recording == {"N1": False}
    assert snap2.links_remaining == 0
    assert snap2.done  # single-inbound node finalizes on first marker


def test_token_sent_before_marker_is_recorded():
    # Classic consistent-cut scenario: token in flight across the cut line.
    sim = make_sim([("N1", 5), ("N2", 0)], [("N1", "N2"), ("N2", "N1")],
                   FixedDelay(3))
    snaps = run_events(sim, [
        PassTokenEvent("N1", "N2", 2),  # rt=3
        SnapshotEvent("N2"),            # N2 freezes 0, records N1->N2
    ])
    assert snaps[0].token_map == {"N1": 3, "N2": 0}
    assert [(m.src, m.dest, m.message.data) for m in snaps[0].messages] == \
        [("N1", "N2", 2)]


def test_concurrent_snapshots_record_independently():
    sim = make_sim(*ring(4), FixedDelay(1))
    events = [SnapshotEvent("N1"), SnapshotEvent("N3"), TickEvent(1),
              PassTokenEvent("N2", "N3", 5)]
    snaps = run_events(sim, events)
    assert {s.id for s in snaps} == {0, 1}
    check_tokens(sim.node_tokens(), snaps)


def test_send_more_than_balance_raises():
    sim = make_sim(*ring(2, tokens=1), FixedDelay(1))
    with pytest.raises(ValueError):
        sim.process_event(PassTokenEvent("N1", "N2", 99))


@pytest.mark.parametrize("trial", range(10))
def test_property_conservation_random_scripts(trial):
    rng = np.random.default_rng(1000 + trial)
    n = int(rng.integers(2, 8))
    ids = [f"N{i+1}" for i in range(n)]
    nodes = [(i, int(rng.integers(0, 30))) for i in ids]
    # strongly-connected base ring + random extra arcs
    links = {(ids[i], ids[(i + 1) % n]) for i in range(n)}
    for _ in range(int(rng.integers(0, n * 2))):
        a, b = rng.choice(n, size=2, replace=False)
        links.add((ids[a], ids[b]))
    outbound = {i: sorted(d for s, d in links if s == i) for i in ids}
    events = []
    for _ in range(int(rng.integers(5, 40))):
        r = rng.random()
        if r < 0.5:
            src = ids[int(rng.integers(n))]
            dests = outbound[src]
            events.append(PassTokenEvent(src, dests[int(rng.integers(len(dests)))], 1))
        elif r < 0.7:
            events.append(SnapshotEvent(ids[int(rng.integers(n))]))
        else:
            events.append(TickEvent(int(rng.integers(1, 4))))
    # Large balances so random sends never overdraw.
    sim = make_sim([(i, 1000) for i in ids], sorted(links), NumpyUniformDelay(trial))
    snaps = run_events(sim, events)
    assert sim.total_tokens() == n * 1000  # conservation incl. in-flight
    # The reference's checkTokens compares against node balances only, so
    # fully drain the network first (the fixtures happen to be drained after
    # the standard flush; random scripts need not be).
    while sim.total_tokens() != sum(sim.node_tokens().values()):
        sim.tick()
    check_tokens(sim.node_tokens(), snaps)
    for s in snaps:
        assert len(s.token_map) == n
        assert {m.dest for m in s.messages} <= set(ids)
