"""memo="prefix" (ISSUE 20): rolling prefix-digest chains, the
PrefixCache LRU, and the speculative-fork differential.

The plane's contract, in test order:

* pack_jobs under a memo="prefix" runner stamps a [P, 32] chain of
  phase-boundary digests over the pooled phase table — chain-sharing
  jobs (same identity seed + byte-equal leading rows) share links
  exactly as deep as their scripts agree, and any semantic-identity
  change (scheduler, delay stream) re-seeds the whole chain;
* PrefixCache is a real LRU over entries AND bytes: insertion-ordered
  eviction, ``get_ckpt`` refreshes recency while ``bump_seen`` heat
  does not, evictions are counted, flush/reload round-trips the
  checkpoint leaves byte-for-byte, and schema skew is refused loudly;
* the fork differential: a near-duplicate queue served by forking from
  cached checkpoints is bit-identical to the memo-off execution of the
  SAME pool (the prefix runner packs; identity is first-phase-keyed,
  so per-arm packing would compare different computations), fork
  provenance rows carry ``served_from="prefix:<depth>"``, the books
  balance (prefix_hits == forked_jobs), and an undersized cache evicts
  — counted in the summary — while evicted prefixes fall back to cold
  admission with results unchanged.

The deep {scheduler} x {faults} sweep and the traced-fork event check
ride the slow marker; the tier-1 keeper here is the small sync-arm
differential (the chaos battery's --prefix-only drill keeps the
fault-armed + poisoned-cache arms in tier-1 via test_chaos_smoke).
"""

import json

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import PassTokenEvent, TickEvent
from chandy_lamport_tpu.models.workloads import ring_topology, stream_jobs
from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.memocache import (
    PREFIXCACHE_SCHEMA_VERSION,
    PrefixCache,
    PrefixCacheError,
)

SPEC = ring_topology(8, tokens=16)


def make_runner(memo="prefix", delay_seed=7, scheduler="sync", **kw):
    cfg = SimConfig.for_workload(snapshots=2, max_recorded=32)
    return BatchedRunner(SPEC, cfg, make_fast_delay("hash", delay_seed), 2,
                         scheduler=scheduler, megatick=2, memo=memo, **kw)


def chain_rows(pool, j):
    s, e = int(pool.job_start[j]), int(pool.job_end[j])
    return [bytes(bytearray(np.asarray(pool.prefix_digest[r]).tolist()))
            for r in range(s, e)]


def node(i):
    return sorted({s for s, _ in SPEC.links})[i]


# ---------------------------------------------------------------------------
# digest chains


def test_prefix_chains_align_and_diverge_at_the_tail():
    # B extends A by one phase pair; C is an exact duplicate of A
    a = [PassTokenEvent(src=node(0), dest=node(1), tokens=1), TickEvent(1)]
    b = a + [PassTokenEvent(src=node(1), dest=node(2), tokens=1),
             TickEvent(1)]
    runner = make_runner()
    pool = runner.pack_jobs([a, b, list(a)], content_keys=True)
    assert pool.prefix_digest is not None
    assert pool.prefix_digest.shape == (pool.kind.shape[0], 32)
    ca, cb, cc = (chain_rows(pool, j) for j in range(3))
    # every boundary digest is stamped (no zero rows inside a script)
    assert all(any(byte for byte in link) for link in ca + cb + cc)
    # the shared prefix shares the chain, link for link...
    assert len(cb) > len(ca)
    assert cb[:len(ca)] == ca
    # ...and the tail diverges immediately after
    assert cb[len(ca)] not in ca
    # an exact duplicate shares the WHOLE chain and the whole-job digest
    assert cc == ca
    assert np.array_equal(np.asarray(pool.digest[2]),
                          np.asarray(pool.digest[0]))
    # near-duplicates share the first-phase identity: same fault/delay
    # stream rows (the packer's chain-sharing precondition)
    for leaf in jax.tree_util.tree_leaves(pool.delay_state):
        assert np.array_equal(np.asarray(leaf)[0], np.asarray(leaf)[1])


def test_prefix_chains_reseed_on_identity_change():
    a = [PassTokenEvent(src=node(0), dest=node(1), tokens=1), TickEvent(1)]
    base = chain_rows(make_runner().pack_jobs([a], content_keys=True), 0)
    for other in (make_runner(scheduler="exact"),
                  make_runner(delay_seed=8)):
        rows = chain_rows(other.pack_jobs([a], content_keys=True), 0)
        # identical script, different execution identity: no link of the
        # chain may alias — a checkpoint must never fork across them
        assert not set(rows) & set(base)


# ---------------------------------------------------------------------------
# PrefixCache LRU (satellite: bytes-capped eviction order + counters)


def leaves_of(v):
    return {"tokens": np.full((8,), v, np.int32),
            "nested": (np.arange(4, dtype=np.int64),
                       np.float32(v))}


def test_prefix_cache_lru_evicts_by_bytes_in_order(tmp_path):
    path = str(tmp_path / "prefix.jsonl")
    probe = PrefixCache(None)
    probe.put_ckpt("a" * 64, 1, leaves_of(1))
    line = probe._line_bytes("a" * 64, probe._entries["a" * 64])
    # room for two checkpoints, not three
    cache = PrefixCache(path, max_bytes=2 * line + line // 2)
    for i, dg in enumerate(("a" * 64, "b" * 64, "c" * 64)):
        cache.put_ckpt(dg, i + 1, leaves_of(i))
    # insertion order IS eviction order: the oldest checkpoint went
    assert "a" * 64 not in cache
    assert "b" * 64 in cache and "c" * 64 in cache
    assert cache.evictions == 1
    assert cache.evicted_bytes >= line
    # a get_ckpt refreshes recency, so the NEXT eviction takes "c"
    depth, leaves = cache.get_ckpt("b" * 64)
    assert depth == 2
    assert np.array_equal(leaves["tokens"], leaves_of(1)["tokens"])
    cache.put_ckpt("d" * 64, 4, leaves_of(3))
    assert "c" * 64 not in cache
    assert "b" * 64 in cache and "d" * 64 in cache
    # flush/reload round-trips the surviving entries byte-for-byte
    cache.flush()
    back = PrefixCache(path)
    assert set(back._entries) == {"b" * 64, "d" * 64}
    _, reloaded = back.get_ckpt("d" * 64)
    assert np.array_equal(reloaded["tokens"], leaves_of(3)["tokens"])
    assert reloaded["nested"][1] == np.float32(3)
    assert reloaded["nested"][0].dtype == np.int64


def test_prefix_cache_seen_heat_does_not_outcompete_checkpoints():
    probe = PrefixCache(None)
    probe.put_ckpt("a" * 64, 1, leaves_of(1))
    line = probe._line_bytes("a" * 64, probe._entries["a" * 64])
    cache = PrefixCache(None, max_bytes=line + line // 2)
    cache.put_ckpt("a" * 64, 1, leaves_of(1))
    # heat-only entries insert at the LRU FRONT: they must be the first
    # casualties, never the checkpoint they were supposed to promote
    cache.bump_seen("b" * 64, 2)
    assert "a" * 64 in cache
    assert cache.seen("b" * 64) in (0, 1)  # may already be evicted
    cache.bump_seen("c" * 64, 2)
    assert "a" * 64 in cache and cache.has_ckpt("a" * 64)


def test_prefix_cache_refuses_schema_skew(tmp_path):
    path = str(tmp_path / "prefix.jsonl")
    cache = PrefixCache(path)
    cache.put_ckpt("a" * 64, 3, leaves_of(1))
    cache.flush()
    with open(path, "r", encoding="utf-8") as f:
        entry = json.loads(f.read())
    entry["schema"] = PREFIXCACHE_SCHEMA_VERSION + 1
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(entry) + "\n")
    with pytest.raises(PrefixCacheError, match="schema version"):
        PrefixCache(path)


# ---------------------------------------------------------------------------
# the fork differential + eviction fallback (tier-1 keeper)


def strip(row):
    return {k: v for k, v in row.items()
            if k not in ("job", "admit_step", "digest", "served_from")}


def test_prefix_fork_differential_and_eviction_fallback(tmp_path):
    """The tier-1 fork keeper: near-duplicate queue, two drives (seed,
    then fork-from-disk), every fork shadow-audited, byte-compared
    against the memo-off oracle on the SAME prefix-packed pool; then
    the cache is capped to one entry and the evicted prefixes must fall
    back to cold admission with the results unchanged and the eviction
    counted in the books. (The deep {scheduler} x {faults} sweep is the
    slow-marker test below; the fault-armed arm stays in tier-1 via the
    chaos battery's --prefix-only drill.)"""
    cache = str(tmp_path / "prefix.jsonl")
    runner = make_runner(prefix_cache=cache)
    jobs = stream_jobs(SPEC, 6, seed=9, base_phases=2, max_phases=5,
                      prefix_overlap=0.5)
    pool = runner.pack_jobs(jobs)
    for _ in range(2):
        state, stream = runner.run_stream(pool, stretch=2, drain_chunk=8,
                                          shadow_every=1)
    sm = runner.summarize_stream(stream)
    assert sm["jobs_done"] == 6
    assert sm["forked_jobs"] > 0
    assert sm["prefix_hits"] == sm["forked_jobs"]   # the books balance
    assert sm["fork_depth_mean"] > 0
    assert sm["shadow_checks"] >= sm["forked_jobs"]  # every fork audited
    res = {r["job"]: r for r in runner.stream_results(stream)}
    forked = {j: r for j, r in res.items()
              if str(r.get("served_from", "")).startswith("prefix:")}
    assert len(forked) == sm["forked_jobs"]
    # provenance depth is a real chain depth within the job's script
    for j, r in forked.items():
        d = int(str(r["served_from"]).split(":")[1])
        assert 1 <= d <= int(pool.job_end[j]) - int(pool.job_start[j])
    # the oracle: a memo-off runner consuming the prefix-packed pool
    oracle = make_runner(memo="off")
    _, ostream = oracle.run_stream(pool, stretch=2, drain_chunk=8)
    ores = {r["job"]: r for r in oracle.stream_results(ostream)}
    assert sorted(res) == sorted(ores)
    for j in ores:
        assert strip(res[j]) == strip(ores[j]), f"job {j} diverged"
    # -- eviction fallback: cap the store at ONE entry (same runner, so
    #    the warm executable is reused; the file handle is rebuilt with
    #    the new caps on the next run) and drive again
    runner.prefix_cache_entries = 1
    _, stream2 = runner.run_stream(pool, stretch=2, drain_chunk=8,
                                   shadow_every=1)
    sm2 = runner.summarize_stream(stream2)
    assert sm2["jobs_done"] == 6
    assert sm2["prefix_evictions"] > 0
    assert sm2["prefix_evicted_bytes"] > 0
    assert sm2["prefix_store_entries"] <= 1
    # evicted prefixes fell back to COLD admission — results unchanged
    res2 = {r["job"]: r for r in runner.stream_results(stream2)}
    for j in ores:
        assert strip(res2[j]) == strip(ores[j]), f"job {j} diverged cold"


# ---------------------------------------------------------------------------
# tools/analyze.py renders the fork books (no engine: synthetic telemetry)


def test_analyze_telemetry_renders_prefix_books(tmp_path, capsys):
    import importlib.util
    import os

    from chandy_lamport_tpu.utils.tracing import TelemetryWriter

    spec = importlib.util.spec_from_file_location(
        "clsim_analyze",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "analyze.py"))
    analyze = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(analyze)
    path = str(tmp_path / "tel.jsonl")
    with TelemetryWriter(path) as tw:
        tw.write("stream_run", {
            "jobs_done": 4, "memo": "prefix", "prefix_hits": 3,
            "forked_jobs": 3, "fork_depth_mean": 2.6667,
            "prefix_evictions": 1, "prefix_speedup": 1.25,
            "fork_depth_hist": {"2": 2, "4": 1}})
        for j in range(4):
            row = {"job": j, "error": 0}
            if j:
                row["served_from"] = f"prefix:{2 * ((j + 1) // 2)}"
            tw.write("stream_job", row)
    analyze.analyze_telemetry(path)
    out = capsys.readouterr().out
    # the run headline carries the fork books + the depth histogram line
    assert "prefix_hits=3" in out and "prefix_speedup=1.25" in out
    assert "prefix_evictions=1" in out
    assert "fork depths: d2:2, d4:1" in out
    # per-job provenance: hit rate over the harvest + decoded depths
    assert "3 prefix-forked (hit rate 0.75; d2:2, d4:1)" in out


# ---------------------------------------------------------------------------
# the deep sweep (slow): {sync, exact} x faults, traced fork events


@pytest.mark.slow
@pytest.mark.parametrize("scheduler", ["sync", "exact"])
def test_prefix_fork_deep_sweep_under_faults(tmp_path, scheduler):
    from chandy_lamport_tpu.models.faults import JaxFaults
    from chandy_lamport_tpu.utils.tracing import (
        EV_PREFIX_FORK,
        JaxTrace,
        decode_trace,
    )

    cfg = SimConfig.for_workload(snapshots=2, max_recorded=64)

    def mk(memo, trace=None):
        return BatchedRunner(
            SPEC, cfg, make_fast_delay("hash", 11), 4,
            scheduler=scheduler, quarantine=True, trace=trace,
            faults=JaxFaults(3, drop_rate=0.05, dup_rate=0.05,
                             jitter_rate=0.05),
            memo=memo,
            prefix_cache=str(tmp_path / f"prefix-{scheduler}.jsonl"))

    runner = mk("prefix", trace=JaxTrace())
    jobs = stream_jobs(SPEC, 12, seed=5, base_phases=4, max_phases=10,
                       prefix_overlap=0.75)
    pool = runner.pack_jobs(jobs)
    for _ in range(2):
        state, stream = runner.run_stream(pool, stretch=2, drain_chunk=8,
                                          shadow_every=1)
    sm = runner.summarize_stream(stream)
    assert sm["jobs_done"] == 12
    assert sm["forked_jobs"] > 0
    assert sm["prefix_hits"] == sm["forked_jobs"]
    assert sm["shadow_checks"] >= sm["forked_jobs"]
    # the flight recorder saw the forks: EV_PREFIX_FORK events whose
    # payload is the fork depth
    host = jax.device_get(state)
    forks = [e for lane in range(4) for e in decode_trace(host, lane=lane)
             if e.kind == EV_PREFIX_FORK]
    assert forks
    assert all(e.payload >= 1 for e in forks)
    res = {r["job"]: r for r in runner.stream_results(stream)}
    oracle = BatchedRunner(
        SPEC, cfg, make_fast_delay("hash", 11), 4, scheduler=scheduler,
        quarantine=True,
        faults=JaxFaults(3, drop_rate=0.05, dup_rate=0.05,
                         jitter_rate=0.05))
    _, ostream = oracle.run_stream(pool, stretch=2, drain_chunk=8)
    ores = {r["job"]: r for r in oracle.stream_results(ostream)}
    assert sorted(res) == sorted(ores)
    for j in ores:
        assert strip(res[j]) == strip(ores[j]), \
            f"{scheduler}: forked job {j} diverged from cold under faults"
    # live fault evidence: this sweep forked through armed adversaries,
    # not a fault-free fast path
    assert any(r.get("fault_events", 0) > 0 for r in ores.values())
