"""Differential coverage for the ring-queue addressing engines (PR 2).

The gather engine (ops/tick.TickKernel queue_engine="gather") reads ring
heads with O(E) ``take_along_axis`` gathers and appends with O(E)
``.at[edge, pos]`` scatters over the packed ``q_meta``/``q_data`` planes;
"mask" is the pre-PR-2 O(E·C) one-hot formulation, kept as the oracle.
The two must be BIT-IDENTICAL — same ring planes, same error bits, same
sampler stream — on every exact formulation (fold, cascade, wave), and
in the three ring regimes that distinguish the addressings: wraparound
(head+len crossing C), full capacity, and a marker at the head.
"""

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import (
    DenseTopology,
    init_state,
    pack_meta,
)
from chandy_lamport_tpu.models.workloads import (
    erdos_renyi,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, HashJaxDelay
from chandy_lamport_tpu.ops.tick import TickKernel
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.compare import dense_state_mismatches

IMPLS = ("fold", "cascade", "wave")


def _kernel_pair(impl, cfg, spec=None, delay=None):
    topo = DenseTopology(spec or erdos_renyi(8, 2.5, seed=7, tokens=50))
    delay = delay or FixedJaxDelay(2)
    return topo, delay, [
        TickKernel(topo, cfg, delay, marker_mode="ring", exact_impl=impl,
                   queue_engine=eng) for eng in ("gather", "mask")]


def _craft(state, topo, cfg, case):
    """Hand-built ring regimes. time stays 0; the tick advances it to 1,
    so rtime=1 heads are exactly-now eligible."""
    e, C = topo.e, cfg.queue_capacity
    q_meta = np.zeros((e, C), np.int32)
    q_data = np.zeros((e, C), np.int32)
    if case == "wrap":
        # head+len crosses C: slots C-1 and 0 occupied
        head = np.full(e, C - 1, np.int32)
        length = np.full(e, 2, np.int32)
        q_meta[:, C - 1] = pack_meta(1, False)
        q_data[:, C - 1] = 5
        q_meta[:, 0] = pack_meta(3, False)
        q_data[:, 0] = 7
    elif case == "full":
        # every slot occupied, head mid-ring
        head = np.full(e, 1, np.int32)
        length = np.full(e, C, np.int32)
        for k in range(C):
            pos = (1 + k) % C
            q_meta[:, pos] = pack_meta(1 + k, False)
            q_data[:, pos] = 10 + k
    else:  # marker_head
        # marker at the head (sid 0), token right behind, wrapped head;
        # the first-receipt broadcast then APPENDS through the engines
        head = np.full(e, C - 1, np.int32)
        length = np.full(e, 2, np.int32)
        q_meta[:, C - 1] = pack_meta(1, True)
        q_data[:, C - 1] = 0
        q_meta[:, 0] = pack_meta(2, False)
        q_data[:, 0] = 3
    return state._replace(q_meta=q_meta, q_data=q_data, q_head=head,
                          q_len=length,
                          tok_pushed=np.asarray(length).copy())


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("case", ["wrap", "full", "marker_head"])
def test_crafted_ring_regimes(impl, case):
    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    topo, delay, kernels = _kernel_pair(impl, cfg)
    finals = []
    for k in kernels:
        s = _craft(init_state(topo, cfg, delay.init_state()), topo, cfg,
                   case)
        s = k.tick(s)            # engine-addressed select/pop (+ appends)
        s = k.tick(s)            # second tick: pops across the wrap point
        finals.append(jax.device_get(s))
    assert dense_state_mismatches(*finals) == []
    if case == "full" and impl != "fold":
        # popped-up-front semantics: a full ring with no same-tick append
        # must NOT flag overflow under either engine
        assert int(finals[0].error) == 0


@pytest.mark.parametrize("impl", IMPLS)
def test_append_rows_partial_active(impl):
    """The batched append primitive directly: a partially-active row on a
    wrapped ring must land the same slots, lengths, and overflow bits
    under both addressings (inactive rows must drop, not write)."""
    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    topo, delay, kernels = _kernel_pair(impl, cfg)
    active = np.arange(topo.e) % 2 == 0
    rt = np.full(topo.e, 9, np.int32)
    data = np.arange(topo.e, dtype=np.int32) + 100
    outs = []
    for k in kernels:
        s = _craft(init_state(topo, cfg, delay.init_state()), topo, cfg,
                   "wrap")
        outs.append(jax.device_get(
            jax.jit(k._append_rows)(s, active, rt, False, data)))
    assert dense_state_mismatches(*outs) == []
    np.testing.assert_array_equal(outs[0].q_len[active], 3)
    np.testing.assert_array_equal(outs[0].q_len[~active], 2)


@pytest.mark.parametrize("impl", IMPLS)
def test_append_rows_overflow_parity(impl):
    """Appending onto a FULL ring flags ERR_QUEUE_OVERFLOW identically
    (and clobbers the same slot) under both engines."""
    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    topo, delay, kernels = _kernel_pair(impl, cfg)
    active = np.ones(topo.e, bool)
    outs = []
    for k in kernels:
        s = _craft(init_state(topo, cfg, delay.init_state()), topo, cfg,
                   "full")
        outs.append(jax.device_get(jax.jit(k._append_rows)(
            s, active, np.full(topo.e, 9, np.int32), False,
            np.int32(1))))
    assert dense_state_mismatches(*outs) == []
    assert int(outs[0].error) != 0


@pytest.mark.parametrize("impl", [
    # fold executes the storm reference-literally (one sequential event at
    # a time) and routes through the same queue primitives cascade does —
    # deep confidence, but ~2x the other two combined, so it rides outside
    # the tier-1 wall-clock budget
    pytest.param("fold", marks=pytest.mark.slow),
    # all three storm legs ride outside the tier-1 wall: the crafted ring
    # regimes + append-row + sync-storm tests above keep per-engine
    # gather-vs-mask coverage in tier-1 at unit cost
    pytest.param("cascade", marks=pytest.mark.slow),
    pytest.param("wave", marks=pytest.mark.slow)])
def test_storm_gather_vs_mask(impl):
    """End-to-end batched storms: the full protocol (injections, marker
    broadcasts, drain — every push/pop path) bit-identical across
    engines, per exact formulation."""
    spec = erdos_renyi(16, 2.5, seed=11, tokens=60)
    cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48)
    finals = []
    for eng in ("gather", "mask"):
        r = BatchedRunner(spec, cfg, HashJaxDelay(seed=31), batch=4,
                          scheduler="exact", exact_impl=impl,
                          queue_engine=eng)
        prog = storm_program(
            r.topo, phases=5, amount=2,
            snapshot_phases=staggered_snapshots(r.topo, 3))
        finals.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    assert int(np.max(finals[0].error)) == 0
    assert dense_state_mismatches(*finals) == []


def test_auto_engine_resolution():
    """queue_engine="auto" resolves per backend (gather where O(E) HBM
    traffic wins, mask where XLA serializes scatters), parameterized so
    the TPU decision is pinned from the CPU mesh — the count_dtype
    pattern."""
    from chandy_lamport_tpu.ops.tick import resolve_queue_engine

    assert resolve_queue_engine("auto", backend="tpu") == "gather"
    assert resolve_queue_engine("auto", backend="cpu") == "mask"
    assert resolve_queue_engine("gather", backend="cpu") == "gather"
    assert resolve_queue_engine("mask", backend="tpu") == "mask"
    with pytest.raises(ValueError):
        resolve_queue_engine("bogus")
    # a live kernel always carries a RESOLVED engine
    cfg = SimConfig(max_snapshots=4, queue_capacity=4, max_recorded=16)
    _, _, kernels = _kernel_pair("cascade", cfg)
    topo = kernels[0].topo
    auto_k = TickKernel(topo, cfg, FixedJaxDelay(2), marker_mode="ring")
    assert auto_k.queue_engine in ("gather", "mask")


def test_sync_scheduler_gather_vs_mask():
    """The split-representation sync tick reads token heads through the
    same engine-addressed primitive — pin it too."""
    spec = erdos_renyi(16, 2.5, seed=13, tokens=60)
    cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48)
    finals = []
    for eng in ("gather", "mask"):
        r = BatchedRunner(spec, cfg, HashJaxDelay(seed=37), batch=4,
                          scheduler="sync", queue_engine=eng)
        prog = storm_program(
            r.topo, phases=5, amount=2,
            snapshot_phases=staggered_snapshots(r.topo, 3))
        finals.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    assert int(np.max(finals[0].error)) == 0
    assert dense_state_mismatches(*finals) == []
