"""Preemption-safe checkpointing and snapshot-rollback recovery.

Three subsystems under test (utils/checkpoint.py):

  * HARDENED LOADS — every way a checkpoint file can be damaged (garbage
    bytes, truncated zip, missing header, stale format version) raises a
    CheckpointError naming the path, never a raw numpy/zipfile traceback;
    saves are atomic (tmp-then-os.replace, no .tmp droppings).
  * KILL-AND-RESUME BIT-EXACTNESS — a storm checkpointed at phase k,
    reloaded, and run to completion matches the uninterrupted run leaf for
    leaf, through the python API (adversary armed, proving the fault
    streams survive resume in ``fault_key``) AND through the storm CLI's
    --checkpoint-every / --kill-after-chunk / --resume-from path.
  * SNAPSHOT-ROLLBACK — ``restore_from_snapshot`` rebuilds a runnable
    state from a completed Chandy-Lamport snapshot's consistent cut, and
    replaying it to quiescence reproduces the original final balances
    bit-exactly; an incomplete snapshot is refused.
"""

import io
import json
import sys

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.cli import main
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.models.faults import JaxFaults
from chandy_lamport_tpu.models.workloads import (
    StormProgram,
    ring_topology,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, make_fast_delay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils import checkpoint as ckpt_mod
from chandy_lamport_tpu.utils.checkpoint import (
    CheckpointError,
    load_state,
    restore_from_snapshot,
    save_state,
)

SPEC = ring_topology(8, tokens=100)
CFG = SimConfig.for_workload(snapshots=2, max_recorded=128)


def _runner(faults=None, batch=2):
    return BatchedRunner(SPEC, CFG, make_fast_delay("hash", 11), batch=batch,
                         scheduler="exact", faults=faults,
                         quarantine=faults is not None)


def _prog(topo, phases=10):
    return storm_program(
        topo, phases=phases, amount=1,
        snapshot_phases=staggered_snapshots(topo, 1, 1, 2,
                                            max_phases=phases))


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                    jax.tree_util.tree_leaves(jax.device_get(b))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---- hardened loads ----------------------------------------------------


def test_save_leaves_no_tmp_dropping(tmp_path):
    r = _runner()
    path = str(tmp_path / "ck.npz")
    save_state(path, r.init_batch())
    assert (tmp_path / "ck.npz").exists()
    assert not (tmp_path / "ck.npz.tmp").exists()


def test_load_garbage_bytes_raises_checkpoint_error(tmp_path):
    path = str(tmp_path / "junk.npz")
    with open(path, "wb") as f:
        f.write(b"this is not a zip archive at all")
    with pytest.raises(CheckpointError, match="junk.npz"):
        load_state(path, _runner().init_batch())


def test_load_truncated_file_raises_checkpoint_error(tmp_path):
    r = _runner()
    path = str(tmp_path / "trunc.npz")
    save_state(path, r.init_batch())
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])      # cut the zip mid-member
    with pytest.raises(CheckpointError, match="trunc.npz"):
        load_state(path, r.init_batch())


def test_load_missing_header_raises_checkpoint_error(tmp_path):
    path = str(tmp_path / "headless.npz")
    np.savez(path, leaf_0=np.zeros(4))       # a real npz, not a checkpoint
    with pytest.raises(CheckpointError, match="__header__"):
        load_state(path, _runner().init_batch())


def test_load_stale_format_version_raises_checkpoint_error(
        tmp_path, monkeypatch):
    r = _runner()
    path = str(tmp_path / "v3.npz")
    monkeypatch.setattr(ckpt_mod, "_FORMAT_VERSION", 3)
    save_state(path, r.init_batch())
    monkeypatch.undo()
    # the error names the offending version AND the supported range, so an
    # operator holding a stale file knows both sides of the mismatch
    with pytest.raises(CheckpointError,
                       match=r"format version 3.*supported version range "
                             r"v\d+\.\.v\d+"):
        load_state(path, r.init_batch())


@pytest.mark.slow  # ~14 s; the cli kill-resume test round-trips every leaf in tier-1
def test_roundtrip_carries_fault_leaves(tmp_path):
    # format v4: the adversary's stream keys and books survive the disk
    # trip, so a resumed faulted run replays the SAME fault program
    r = _runner(JaxFaults(3, drop_rate=0.05, dup_rate=0.05))
    final = r.run_storm(r.init_batch(), _prog(r.topo))
    path = str(tmp_path / "faulted.npz")
    save_state(path, final, meta={"note": "faulted"})
    restored, meta = load_state(path, r.init_batch())
    assert meta["note"] == "faulted"
    assert np.any(np.asarray(restored.fault_key))
    _assert_trees_equal(final, restored)


@pytest.mark.slow  # ~17 s; test_cli_storm_kill_resume_bit_exact round-trips the
# FULL current-format state (every leaf bit-exact through save/load +
# resume) in tier-1 — this leg pins the v5 supervisor-leaf detail
def test_v5_roundtrip_carries_supervisor_leaves(tmp_path):
    # format v5: the snapshot supervisor's books (epochs, deadlines,
    # retries, initiators, completion ticks, stale tallies) survive the
    # disk trip — a resumed run's timeout scan picks up EXACTLY where the
    # killed one left off. The marker-drop adversary guarantees the saved
    # state actually carries nonzero retry/epoch values.
    import dataclasses

    cfg = dataclasses.replace(CFG, snapshot_timeout=12, snapshot_retries=5)
    faults = JaxFaults(3, marker_drop_rate=0.2)
    r = BatchedRunner(SPEC, cfg, make_fast_delay("hash", 11), batch=2,
                      scheduler="exact", faults=faults, quarantine=True)
    final = r.run_storm(r.init_batch(), _prog(r.topo, phases=8))
    host = np.asarray(jax.device_get(final.snap_retries))
    assert host.sum() > 0, "fixture must exercise the retry path"
    assert np.all(np.asarray(jax.device_get(final.snap_initiator))[
        np.asarray(jax.device_get(final.started))] >= 0)
    path = str(tmp_path / "supervised.npz")
    save_state(path, final, meta={"note": "v5"})
    restored, meta = load_state(path, r.init_batch())
    assert meta["note"] == "v5"
    _assert_trees_equal(final, restored)
    for leaf in ("snap_epoch", "snap_deadline", "snap_retries",
                 "snap_initiator", "snap_failed", "snap_done_time",
                 "stale_markers"):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(getattr(final, leaf))),
            np.asarray(jax.device_get(getattr(restored, leaf))))


# ---- kill-and-resume bit-exactness (python API) ------------------------


@pytest.mark.slow  # ~18 s; the cli kill-resume bit-exact test stays tier-1
def test_kill_and_resume_bit_exact_with_adversary(tmp_path):
    adversary = JaxFaults(5, drop_rate=0.03, dup_rate=0.03,
                          jitter_rate=0.03)
    r = _runner(adversary)
    prog = _prog(r.topo, phases=12)
    uninterrupted = r.run_storm(r.init_batch(), prog)

    # "preemption" at phase 6: checkpoint, forget everything, reload into
    # a FRESH runner (fresh jit caches — nothing survives but the file),
    # run the remaining phases, drain
    amounts, snap = np.asarray(prog.amounts), np.asarray(prog.snap)
    first = StormProgram(amounts[:6], snap[:6])
    rest = StormProgram(amounts[6:], snap[6:])
    mid = r.run_storm(r.init_batch(), first, drain=False)
    path = str(tmp_path / "preempt.npz")
    save_state(path, mid, meta={"next_phase": 6})

    r2 = _runner(adversary)
    resumed, meta = load_state(path, r2.init_batch())
    assert meta["next_phase"] == 6
    final2 = r2.drain(r2.run_storm(resumed, rest, drain=False))
    _assert_trees_equal(uninterrupted, final2)


# ---- kill-and-resume bit-exactness (storm CLI) -------------------------


def _capture(argv):
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        code = main(argv)
    finally:
        sys.stdout = old
    return code, out.getvalue()


def test_cli_storm_kill_resume_bit_exact(tmp_path):
    base = ["storm", "--graph", "ring", "--nodes", "8", "--batch", "2",
            "--phases", "9", "--snapshots", "1", "--seed", "3"]
    ref = str(tmp_path / "ref.npz")
    code, out = _capture(base + ["--checkpoint", ref])
    assert code == 0, out
    ref_counters = json.loads(out)

    # chunked run killed right after the first 3-phase chunk's checkpoint
    ck = str(tmp_path / "mid.npz")
    fin = str(tmp_path / "resumed.npz")
    code, out = _capture(base + ["--checkpoint", ck,
                                 "--checkpoint-every", "3",
                                 "--kill-after-chunk", "0"])
    assert code == 17                        # the deterministic "kill"
    assert json.loads(out.splitlines()[-1])["killed_after_phase"] == 3

    code, out = _capture(base + ["--checkpoint", fin,
                                 "--checkpoint-every", "3",
                                 "--resume-from", ck])
    assert code == 0, out
    resumed_counters = json.loads(out.splitlines()[-1])
    resumed_counters.pop("checkpoint"), ref_counters.pop("checkpoint")
    assert resumed_counters == ref_counters

    # bit-exact: compare the two final checkpoints leaf for leaf
    with np.load(ref) as za, np.load(fin) as zb:
        assert set(za.files) == set(zb.files)
        for name in za.files:
            if name == "__header__":
                continue                     # meta differs (next_phase etc.)
            np.testing.assert_array_equal(za[name], zb[name])


@pytest.mark.slow
def test_cli_storm_kill_resume_bit_exact_under_marker_faults(tmp_path):
    # ISSUE 4 acceptance: the v5 carry holds the supervisor's deadlines/
    # epochs/retry budgets and the marker-fault stream key, so a kill
    # right after a chunk checkpoint and a resume land bit-identically on
    # the uninterrupted run — mid-retry, marker drops and all
    base = ["storm", "--graph", "ring", "--nodes", "8", "--batch", "2",
            "--phases", "9", "--snapshots", "1", "--seed", "3",
            "--marker-fault-drop", "0.15", "--snapshot-timeout", "16",
            "--snapshot-retries", "8"]
    ref = str(tmp_path / "mref.npz")
    code, out = _capture(base + ["--checkpoint", ref])
    assert code == 0, out
    ref_counters = json.loads(out.splitlines()[-1])

    ck = str(tmp_path / "mmid.npz")
    fin = str(tmp_path / "mresumed.npz")
    code, out = _capture(base + ["--checkpoint", ck,
                                 "--checkpoint-every", "3",
                                 "--kill-after-chunk", "0"])
    assert code == 17
    code, out = _capture(base + ["--checkpoint", fin,
                                 "--checkpoint-every", "3",
                                 "--resume-from", ck])
    assert code == 0, out
    resumed_counters = json.loads(out.splitlines()[-1])
    resumed_counters.pop("checkpoint"), ref_counters.pop("checkpoint")
    assert resumed_counters == ref_counters
    with np.load(ref) as za, np.load(fin) as zb:
        assert set(za.files) == set(zb.files)
        for name in za.files:
            if name == "__header__":
                continue
            np.testing.assert_array_equal(za[name], zb[name])


def test_cli_storm_resume_rejects_corrupt_checkpoint(tmp_path):
    bad = str(tmp_path / "bad.npz")
    with open(bad, "wb") as f:
        f.write(b"\x00" * 64)
    with pytest.raises(CheckpointError, match="bad.npz"):
        _capture(["storm", "--graph", "ring", "--nodes", "8", "--batch", "2",
                  "--phases", "6", "--snapshots", "1",
                  "--resume-from", bad])


# ---- snapshot-rollback recovery ----------------------------------------


def _lane0(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[0],
                                  jax.device_get(tree))


def test_restore_from_snapshot_replays_to_original_balances(tmp_path):
    # ring of 8, snapshot initiated at the LAST send phase (after that
    # phase's sends), so every message in the system is pre-cut: the cut
    # (frozen balances + recorded in-flight messages) plus replay must
    # land exactly on the uninterrupted run's final balances. With sends
    # after the cut that would not hold — post-marker sends belong to the
    # next epoch, not the snapshot.
    r = BatchedRunner(SPEC, CFG, FixedJaxDelay(1), batch=1,
                      scheduler="exact")
    prog = storm_program(
        r.topo, phases=10, amount=1,
        snapshot_phases=staggered_snapshots(r.topo, 1, 9, 1, max_phases=10))
    final = _lane0(r.run_storm(r.init_batch(), prog))
    assert int(final.error) == 0
    assert int(np.asarray(final.completed)[0]) == r.topo.n

    restored = restore_from_snapshot(r.topo, CFG, final, sid=0,
                                     delay_state=FixedJaxDelay(1).init_state())
    # the cut conserves: frozen balances + recorded in-flight == final total
    assert (int(np.asarray(restored.tokens).sum())
            + int(np.asarray(restored.q_data)[
                np.asarray(restored.q_len) > 0].sum())
            >= int(np.asarray(final.tokens).sum()))
    replayed = r.kernel.run_ticks(jax.device_put(restored), np.int32(200))
    replayed = jax.device_get(replayed)
    assert not np.any(np.asarray(replayed.q_len))          # fully drained
    np.testing.assert_array_equal(np.asarray(replayed.tokens),
                                  np.asarray(final.tokens))


def test_restore_from_snapshot_refuses_incomplete_cut():
    r = BatchedRunner(SPEC, CFG, FixedJaxDelay(1), batch=1,
                      scheduler="exact")
    fresh = _lane0(r.init_batch())           # no snapshot ever started
    with pytest.raises(CheckpointError, match="not a completed"):
        restore_from_snapshot(r.topo, CFG, fresh, sid=0)
