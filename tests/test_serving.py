"""clsim-serve (online serving front-end): admission, caches, resume.

The serving contract extends the memo plane's: every admission path —
EDF- or fifo-ordered lane execution, warm-SummaryCache ingest service,
duplicate coalescing, quota refusal — must leave the per-job result rows
BIT-IDENTICAL to the same content-keyed pool on the plain stream path
(the device tick sequence is slot- and admission-independent), and a
serve process killed mid-stream must resume onto the byte-identical
final carry. The host-side planners (``serve_workload``,
``order_eligible``, ``plan_ingest``) are pure and tested directly; the
end-to-end runs share the session runner and ONE module-scoped
``ExecutableCache`` so the serve step compiles once for the whole file
(the disk round-trip then re-materializes it the way a restarted server
would). The deep quota differential re-shapes the exec order (a second
compile) and is ``slow``.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import jax

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.models.workloads import (
    ServeRequest,
    ring_topology,
    serve_workload,
)
from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.serving import (
    SERVE_SCHEMA_VERSION,
    ExecutableCache,
    order_eligible,
    plan_ingest,
    resolve_serve_policy,
    serve_run,
)
from chandy_lamport_tpu.utils.checkpoint import load_state
from chandy_lamport_tpu.utils.memocache import SummaryCache
from chandy_lamport_tpu.utils.tracing import (
    TELEMETRY_SCHEMA_VERSION,
    TelemetryWriter,
    read_telemetry,
)

TOPO = ring_topology(8)
CFG = SimConfig.for_workload(snapshots=4, max_recorded=128)
J, B = 12, 4
TENANTS = 3


def _delay():
    return make_fast_delay("hash", 11)


def _strip(row):
    """Drop the admission- and provenance-dependent keys; the rest must
    be bit-identical across every admission path."""
    return {k: v for k, v in row.items()
            if k not in ("admit_step", "digest", "served_from")}


@pytest.fixture(scope="module")
def runner(ring8_sync_stream_runner):
    # the session-scoped shared instance (conftest): serve mode adds its
    # own jitted step (serve=True jit key), compiled once per session
    return ring8_sync_stream_runner


@pytest.fixture(scope="module")
def requests():
    return serve_workload(TOPO, J, seed=3, rate=0.5, tenants=TENANTS,
                          priorities=2, deadline_slack=(64, 256),
                          dup_rate=0.3, base_phases=3, max_phases=12)


@pytest.fixture(scope="module")
def exec_cache(tmp_path_factory):
    # disk-backed from the start: the reference run below persists its
    # lowered artifact, and the round-trip test re-loads it cold
    return ExecutableCache(str(tmp_path_factory.mktemp("serve-exec")))


@pytest.fixture(scope="module")
def serve_ref(runner, requests, exec_cache):
    """The reference EDF serve run: the one fresh serve-step compile in
    this module (later runs hit the cache's memory plane)."""
    state, stream, report = serve_run(runner, requests, policy="edf",
                                      stretch=3, drain_chunk=16,
                                      exec_cache=exec_cache)
    return state, stream, report, runner.stream_results(stream)


# -- host-side planners (pure, jax-free) --------------------------------


def test_serve_workload_poisson_deterministic():
    a = serve_workload(TOPO, J, seed=3, rate=0.5, tenants=TENANTS,
                       priorities=2, dup_rate=0.3, max_phases=12)
    b = serve_workload(TOPO, J, seed=3, rate=0.5, tenants=TENANTS,
                       priorities=2, dup_rate=0.3, max_phases=12)
    assert a == b, "seeded Poisson/Zipf trace is not deterministic"
    c = serve_workload(TOPO, J, seed=4, rate=0.5, tenants=TENANTS,
                       priorities=2, dup_rate=0.3, max_phases=12)
    assert [r.arrival_step for r in a] != [r.arrival_step for r in c] \
        or [r.events for r in a] != [r.events for r in c]
    assert [r.job for r in a] == list(range(J))
    arr = [r.arrival_step for r in a]
    assert arr == sorted(arr), "requests must come back in arrival order"
    for r in a:
        assert 0 <= r.tenant < TENANTS and r.priority in (0, 1)
        assert 64 <= r.deadline_step - r.arrival_step <= 256


def test_edf_orders_priority_then_deadline():
    def req(job, arrival, prio, deadline):
        return ServeRequest(job=job, arrival_step=arrival, tenant=0,
                            priority=prio, deadline_step=deadline,
                            events=[])
    rs = [req(0, 0, 0, 50), req(1, 2, 1, 90), req(2, 1, 1, 40),
          req(3, 3, 0, 10), req(4, 0, 1, 40)]
    edf = [r.job for r in order_eligible(rs, "edf")]
    # priority class first (higher wins), then earliest deadline; the
    # (arrival, job) tiebreak makes jobs 2 vs 4 (same class+deadline)
    # deterministic
    assert edf == [4, 2, 1, 3, 0]
    fifo = [r.job for r in order_eligible(rs, "fifo")]
    assert fifo == [0, 4, 2, 1, 3]
    with pytest.raises(ValueError, match="serve_policy must be one of"):
        resolve_serve_policy("sjf")


def test_plan_ingest_quota_refuses_without_starving():
    def req(job, tenant):
        return ServeRequest(job=job, arrival_step=job, tenant=tenant,
                            priority=0, deadline_step=job + 64, events=[])
    # tenant 0 floods (5 requests, quota 2); tenant 1 is quota-free
    rs = [req(0, 0), req(1, 1), req(2, 0), req(3, 0), req(4, 1),
          req(5, 0), req(6, 0)]
    digests = [("%02d" % j) * 32 for j in range(len(rs))]
    plan = plan_ingest(rs, digests, SummaryCache(None), quotas=[2, 0])
    # refusal at INGEST in arrival order: the first two tenant-0 arrivals
    # win, the rest are refused; tenant 1 is never starved
    assert plan["status"] == ["exec", "exec", "exec", "refused", "exec",
                              "refused", "refused"]
    assert plan["accepted"] == {0: 2, 1: 2}
    assert plan["refused"] == {0: 3}


def test_plan_ingest_coalesces_and_serves_warm_cache():
    def req(job, tenant=0):
        return ServeRequest(job=job, arrival_step=job, tenant=tenant,
                            priority=0, deadline_step=job + 64, events=[])
    rs = [req(j) for j in range(4)]
    digests = ["aa" * 32, "bb" * 32, "aa" * 32, "cc" * 32]
    warm = SummaryCache(None)
    warm.put("cc" * 32, {"time": 7, "error": 0})
    plan = plan_ingest(rs, digests, warm)
    # first 'aa' leads, second coalesces; 'cc' is served at ingest
    assert plan["status"] == ["exec", "exec", "follower", "cache"]
    assert plan["leader_of"][2] == 0 and plan["followers"][0] == [2]
    assert plan["cache_hit"][3]["time"] == 7
    assert plan["exec"] == [0, 1]


def test_serve_run_validates_inputs(runner, requests):
    bad = [requests[0]._replace(job=5)] + list(requests[1:])
    with pytest.raises(ValueError, match="job"):
        serve_run(runner, bad)
    with pytest.raises(ValueError, match="results_capacity"):
        serve_run(runner, requests, results_capacity=2)


def test_exec_cache_bucket_digest_sensitivity(runner, requests, exec_cache):
    # the bucket must move with anything that changes the traced program
    a = exec_cache.bucket_digest(runner, 3, 16, (np.int32(0),))
    assert a == exec_cache.bucket_digest(runner, 3, 16, (np.int32(0),))
    assert a != exec_cache.bucket_digest(runner, 4, 16, (np.int32(0),))
    assert a != exec_cache.bucket_digest(runner, 3, 8, (np.int32(0),))
    assert a != exec_cache.bucket_digest(
        runner, 3, 16, (np.zeros(2, np.int32),))


# -- end-to-end: the serving loop over the device step ------------------


def test_serve_rows_match_solo_execution(runner, serve_ref, requests):
    """The acceptance bit-identity: every served row — lane-executed,
    coalesced, or (here) cold — equals the plain stream path's row for
    the same content-keyed pool."""
    _, _, report, rows = serve_ref
    assert len(rows) == J and report["served_total"] == J
    pool = runner.pack_jobs([r.events for r in requests],
                            content_keys=True)
    _, st = runner.run_stream(pool, stretch=3, drain_chunk=16)
    base = {r["job"]: r for r in runner.stream_results(st)}
    for row in rows:
        assert _strip(row) == _strip(base[row["job"]]), row["job"]
    # dup_rate 0.3 guarantees the coalesce fan-out path actually ran
    assert report["served_coalesced"] > 0
    assert any(r.get("served_from") == "coalesce" for r in rows)


def test_serve_report_books(serve_ref):
    _, stream, report, rows = serve_ref
    assert report["serve_schema"] == SERVE_SCHEMA_VERSION
    assert report["killed"] is False and report["policy"] == "edf"
    assert report["exec_jobs"] + report["served_cache"] \
        + report["served_coalesced"] == J
    assert report["refused_total"] == 0
    assert 0.0 < report["occupancy"] <= 1.0
    assert report["admit_p50"] is not None \
        and report["admit_p99"] >= report["admit_p50"] >= 0
    assert report["deadline_misses"] >= 0
    # the device tenant book counts lane-served jobs only (cache and
    # coalesce service never burns a lane)
    assert sum(report["tenant_served"]) == report["exec_jobs"]
    assert int(stream.jobs_done) == report["exec_jobs"]
    assert report["warmup_source"] == "fresh" and report["warmup_persisted"]
    assert report["memo_hit_rate"] == round(
        (report["served_cache"] + report["served_coalesced"]) / J, 4)


def test_serve_fifo_same_rows_as_edf(runner, requests, exec_cache,
                                     serve_ref):
    # the policy only permutes admission; the per-job rows are identical
    # (and the executable comes from the cache's memory plane — the
    # policy is a host-side knob, not a trace input)
    _, stream, report, ref_rows = serve_ref
    _, st2, rep2 = serve_run(runner, requests, policy="fifo",
                             stretch=3, drain_chunk=16,
                             exec_cache=exec_cache)
    assert rep2["warmup_source"] == "memory"
    rows2 = {r["job"]: r for r in runner.stream_results(st2)}
    for row in ref_rows:
        assert _strip(row) == _strip(rows2[row["job"]])


def test_serve_telemetry_rows(runner, requests, exec_cache, tmp_path):
    path = str(tmp_path / "serve.jsonl")
    w = TelemetryWriter(path)
    try:
        serve_run(runner, requests, policy="edf", stretch=3,
                  drain_chunk=16, exec_cache=exec_cache,
                  telemetry=w, telemetry_interval=4)
    finally:
        w.close()
    rows = read_telemetry(path)
    kinds = [r["kind"] for r in rows]
    assert kinds.count("serve_interval") >= 1
    assert kinds[-1] == "serve_run"
    for r in rows:
        assert r["schema"] == TELEMETRY_SCHEMA_VERSION
        assert r["serve_schema"] == SERVE_SCHEMA_VERSION
    iv = next(r for r in rows if r["kind"] == "serve_interval")
    for key in ("step", "occupancy", "deadline_misses", "admit_p50",
                "admit_p99", "memo_hit_rate", "tenant_served"):
        assert key in iv, key


def test_serve_kill_resume_bit_exact(runner, requests, exec_cache,
                                     serve_ref, tmp_path):
    """A serve process killed mid-stream resumes onto the byte-identical
    final carry: rows AND every StreamState leaf (counters, books, the
    results ring) match the uninterrupted reference run."""
    _, ref_stream, _, ref_rows = serve_ref
    ck = str(tmp_path / "serve-ck.npz")
    _, _, repA = serve_run(runner, requests, policy="edf", stretch=3,
                           drain_chunk=16, exec_cache=exec_cache,
                           checkpoint=ck, checkpoint_every=3,
                           kill_after_saves=1)
    assert repA["killed"] and os.path.exists(ck)
    pool = runner.pack_jobs([r.events for r in requests],
                            content_keys=True)
    like = (runner.init_batch(),
            runner.init_stream(pool, tenants=TENANTS))
    (sR, stR), meta = load_state(ck, like)
    assert meta["serve_schema"] == SERVE_SCHEMA_VERSION
    assert int(stR.jobs_done) < int(ref_stream.jobs_done)
    _, stB, repB = serve_run(runner, requests, policy="edf", stretch=3,
                             drain_chunk=16, exec_cache=exec_cache,
                             state=sR, stream=stR)
    assert not repB["killed"]
    rowsB = {r["job"]: r for r in runner.stream_results(stB)}
    assert rowsB == {r["job"]: r for r in ref_rows}
    for name in stB._fields:
        a = np.asarray(getattr(stB, name))
        b = np.asarray(getattr(ref_stream, name))
        assert np.array_equal(a, b), (name, a, b)


def test_exec_cache_disk_roundtrip(runner, requests, exec_cache,
                                   serve_ref):
    """A RESTARTED server (fresh ExecutableCache on the same directory —
    empty memory plane) re-materializes the serve step from the
    persisted jax.export artifact instead of re-tracing, and the
    deserialized executable produces bit-identical rows."""
    _, _, _, ref_rows = serve_ref
    ec2 = ExecutableCache(exec_cache.path)
    _, st2, rep2 = serve_run(runner, requests, policy="edf", stretch=3,
                             drain_chunk=16, exec_cache=ec2)
    assert rep2["warmup_source"] == "disk", ec2.last
    assert {r["job"]: r for r in runner.stream_results(st2)} \
        == {r["job"]: r for r in ref_rows}


def test_warm_summary_cache_serves_at_ingest(requests, tmp_path):
    """A warm SummaryCache turns every request into ingest-time service:
    the second run burns zero lanes (and needs no executable at all) yet
    returns the first run's rows bit-identically."""
    cache = str(tmp_path / "memo.jsonl")

    def mk():
        r = BatchedRunner(TOPO, CFG, _delay(), B, scheduler="sync",
                          memo_cache=cache)
        return r

    r1 = mk()
    ec = ExecutableCache(None)
    _, st1, rep1 = serve_run(r1, requests, policy="edf", stretch=2,
                             drain_chunk=8, exec_cache=ec)
    rows1 = {r["job"]: r for r in r1.stream_results(st1)}
    r2 = mk()
    _, st2, rep2 = serve_run(r2, requests, policy="edf", stretch=2,
                             drain_chunk=8, exec_cache=ec)
    assert rep2["exec_jobs"] == 0 and rep2["served_cache"] == J
    assert rep2["memo_hit_rate"] == 1.0 and rep2["steps"] == 0
    rows2 = {r["job"]: r for r in r2.stream_results(st2)}
    assert {j: _strip(r) for j, r in rows2.items()} \
        == {j: _strip(r) for j, r in rows1.items()}
    for r in rows2.values():
        assert r["served_from"] == "cache"


@pytest.mark.slow
def test_serve_deep_quota_differential():
    """The deepest serve differential: a bigger heavy-tailed trace with a
    flooding tenant under quota, both policies, against the solo stream
    oracle — refusals must hit only the quota'd tenant (no starvation),
    and every served row must stay bit-identical to the plain path."""
    reqs = serve_workload(TOPO, 24, seed=11, rate=1.0, tenants=4,
                          priorities=3, deadline_slack=(32, 128),
                          dup_rate=0.4, base_phases=3, max_phases=12)
    quotas = [3, 0, 2, 0]
    runner = BatchedRunner(TOPO, CFG, _delay(), B, scheduler="sync")
    pool = runner.pack_jobs([r.events for r in reqs], content_keys=True)
    _, st_ref = runner.run_stream(pool, stretch=3, drain_chunk=16)
    base = {r["job"]: r for r in runner.stream_results(st_ref)}

    per_tenant = {t: sum(1 for r in reqs if r.tenant == t)
                  for t in range(4)}
    ec = ExecutableCache(None)
    reports = {}
    for policy in ("edf", "fifo"):
        _, st, rep = serve_run(runner, reqs, policy=policy, quotas=quotas,
                               stretch=3, drain_chunk=16, exec_cache=ec)
        reports[policy] = rep
        rows = runner.stream_results(st)
        refused = {int(t): c for t, c in rep["refused_by_tenant"].items()}
        # quota-free tenants are never starved by the flood
        assert all(t in (0, 2) for t in refused), refused
        for t, q in enumerate(quotas):
            if q and per_tenant[t] > q:
                assert refused.get(t) == per_tenant[t] - q
        assert rep["served_total"] == 24 - rep["refused_total"]
        assert len(rows) == rep["served_total"]
        served_jobs = {r["job"] for r in rows}
        for t in (1, 3):
            for r in reqs:
                if r.tenant == t:
                    assert r.job in served_jobs, (t, r.job)
        for row in rows:
            assert _strip(row) == _strip(base[row["job"]]), row["job"]
    # both policies admit the same accepted set, so the books agree
    assert reports["edf"]["refused_by_tenant"] \
        == reports["fifo"]["refused_by_tenant"]
    assert reports["edf"]["served_total"] == reports["fifo"]["served_total"]
