"""The fault-tolerant snapshot plane: epoch-tagged markers, stale
rejection, and the timeout/retry supervisor (ISSUE 4).

Five claims:

  1. ARMED-IDLE IS EXACT — with the supervisor armed but never firing
     (huge timeout) all 7 reference goldens stay bit-identical to the
     unsupervised kernels, and a storm's final state matches the
     supervisor-off run on every leaf except the supervisor's own
     bookkeeping (deadlines/initiators, which exist only when armed).
  2. STALE EPOCHS ARE REJECTED — a ring marker from a superseded attempt
     (the abort bumped ``snap_epoch``) is counted in ``stale_markers``
     and handled by nobody: it cannot re-create local snapshots or close
     the fresh attempt's recording windows.
  3. TIMEOUT → RETRY → COMPLETE, DETERMINISTICALLY — under sustained
     marker loss every initiated snapshot completes via supervisor retry,
     the whole run replays bit-exactly from its seed (fresh traces
     included), and exhausting the retry budget raises
     ERR_SNAPSHOT_TIMEOUT on the exhausted lane only, surfaced through
     ``decode_error_bits`` in the storm CLI's JSON.
  4. THE DAEMON KEEPS THE RECOVERY LINE FRESH — ``snapshot_every``
     initiates (and completes) snapshots with no scheduled initiations at
     all, on the batched AND the graph-sharded runner, and the
     recovery-line age metric reads from it.
  5. CONSTRUCTION CONTRACTS — the reference-literal 'fold' refuses a
     supervisor; bad marker rates are rejected at JaxFaults construction.

The deepest differentials (golden parity x7, the sync-scheduler twin of
the storm parity) carry the ``slow`` marker — tools/chaos_smoke.py keeps
the tier-1 wall covered with the same claims.
"""

import dataclasses

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.api import run_events_file
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import (
    ERR_SNAPSHOT_TIMEOUT,
    decode_error_bits,
    init_state,
)
from chandy_lamport_tpu.models.faults import JaxFaults
from chandy_lamport_tpu.models.workloads import (
    ring_topology,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, make_fast_delay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.compare import (
    assert_snapshots_equal,
    sort_snapshots,
)
from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path

SPEC = ring_topology(8, tokens=100)
CFG = SimConfig.for_workload(snapshots=2, max_recorded=128)
SUP = dataclasses.replace(CFG, snapshot_timeout=24, snapshot_retries=10)
BATCH = 4


def _storm(cfg, faults=None, scheduler="exact", phases=24, runner=None,
           delay=None):
    if runner is None:
        runner = BatchedRunner(SPEC, cfg, delay or FixedJaxDelay(1),
                               batch=BATCH, scheduler=scheduler,
                               faults=faults,
                               quarantine=faults is not None)
    prog = storm_program(
        runner.topo, phases=phases, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, 1, 1, 2,
                                            max_phases=phases))
    return runner, jax.device_get(runner.run_storm(runner.init_batch(),
                                                   prog))


# ---- claim 1: armed-idle is exact --------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("top,events,snaps", REFERENCE_TESTS,
                         ids=[t[1].removesuffix(".events")
                              for t in REFERENCE_TESTS])
def test_armed_supervisor_keeps_goldens_bit_exact(top, events, snaps):
    cfg = SimConfig(snapshot_timeout=50_000, snapshot_retries=3)
    actual, _ = run_events_file(fixture_path(top), fixture_path(events),
                                backend="jax", config=cfg)
    expected = [read_snapshot_file(fixture_path(f)) for f in snaps]
    assert len(actual) == len(expected)
    for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
        assert_snapshots_equal(e, a)


def _sans_sup_bookkeeping(state):
    # deadlines and initiators are recorded only when the supervisor is
    # armed — they ARE the supervisor's state, not the protocol's; every
    # other leaf (epochs, retries, completion ticks, the whole cut) must
    # match the unsupervised run bit for bit
    return jax.tree_util.tree_leaves(state._replace(
        snap_deadline=0, snap_initiator=0))


@pytest.mark.slow
@pytest.mark.parametrize("scheduler", ["exact", "sync"])
def test_armed_idle_storm_bit_identical_to_off(scheduler):
    # the timeout/retry/epoch tier-1 tests below pin the supervisor's
    # active behavior at unit cost; the armed-idle≡off storm rides in
    # full passes
    _, off = _storm(CFG, scheduler=scheduler)
    big = dataclasses.replace(CFG, snapshot_timeout=50_000,
                              snapshot_retries=3)
    _, armed = _storm(big, scheduler=scheduler)
    for a, b in zip(_sans_sup_bookkeeping(off), _sans_sup_bookkeeping(armed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---- claim 2: stale-epoch rejection ------------------------------------


def test_stale_epoch_markers_rejected():
    # hand-build the post-abort race: initiate a snapshot (epoch-0 markers
    # land in the rings), then apply exactly what the supervisor's abort
    # does — bump the epoch, clear the cut — and let the stragglers drain.
    # They must die counted, not handled.
    from chandy_lamport_tpu.core.dense import DenseSim

    sim = DenseSim(SPEC, FixedJaxDelay(1), config=SUP)
    k = sim.kernel
    s = k.inject_snapshot(sim.state, np.int32(0))
    s = jax.device_get(s)
    assert int(np.asarray(s.q_len).sum()) == 1      # ring-8: one marker out
    patched = s._replace(
        snap_epoch=np.asarray(s.snap_epoch).copy() * 0 + np.int32(
            np.arange(len(s.snap_epoch)) == 0),     # epoch[0] = 1
        has_local=np.zeros_like(np.asarray(s.has_local)),
        recording=np.zeros_like(np.asarray(s.recording)),
        rem=np.zeros_like(np.asarray(s.rem)),
        frozen=np.zeros_like(np.asarray(s.frozen)),
    )
    out = jax.device_get(k.run_ticks(jax.device_put(patched), np.int32(20)))
    assert int(out.stale_markers) == 1
    # the stale marker created nothing and closed nothing
    assert not np.any(np.asarray(out.has_local))
    assert not np.any(np.asarray(out.recording))
    assert int(np.asarray(out.q_len).sum()) == 0    # drained, not wedged


# ---- claim 3: timeout -> retry -> complete, deterministically ----------


@pytest.mark.slow
def test_marker_loss_recovers_via_retry_and_replays_bit_exactly():
    # tier-1 carries the retry->complete claim via tools/chaos_smoke.py's
    # marker-drop-retry scenario; the three-storm replay differential
    # (same trace, then fresh traces) runs in full passes
    adversary = JaxFaults(3, marker_drop_rate=0.1)
    runner, a = _storm(SUP, adversary)
    lc = BatchedRunner.summarize(a)["snapshot_lifecycle"]
    assert lc["retried"] > 0, lc                    # the storm actually bit
    assert lc["completed"] == lc["initiated"], lc   # and retry recovered it
    assert not np.any(np.asarray(a.error))
    # same trace, same keys -> bit-identical replay
    _, b = _storm(SUP, adversary, runner=runner)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # fresh runner (fresh XLA traces — nothing survives but the seed):
    # still bit-identical, the replay-from-seed property
    _, c = _storm(SUP, JaxFaults(3, marker_drop_rate=0.1))
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(c)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_exhausted_retries_raise_snapshot_timeout_only():
    # tier-1 carries exhaustion through the CLI test below and the chaos
    # battery's marker-drop-exhausted scenario
    cfg = dataclasses.replace(CFG, snapshot_timeout=10, snapshot_retries=2)
    _, final = _storm(cfg, JaxFaults(3, marker_drop_rate=1.0), phases=16)
    errs = np.asarray(final.error)
    assert np.all(errs & ERR_SNAPSHOT_TIMEOUT)
    assert decode_error_bits(int(errs[0])) == ["ERR_SNAPSHOT_TIMEOUT"]
    lc = BatchedRunner.summarize(final)["snapshot_lifecycle"]
    assert lc["failed"] > 0 and lc["completed"] == 0
    # quarantined: the lanes froze instead of grinding to ERR_TICK_LIMIT
    assert np.all(np.asarray(final.time) < CFG.max_ticks)


def test_cli_storm_surfaces_snapshot_timeout(capsys):
    import json

    from chandy_lamport_tpu.cli import main

    rc = main(["storm", "--graph", "ring", "--nodes", "8", "--batch", "2",
               "--phases", "8", "--snapshots", "1", "--seed", "3",
               "--marker-fault-drop", "1.0", "--snapshot-timeout", "8",
               "--snapshot-retries", "1"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    counters = json.loads(out)
    # the injured lanes are quarantined with the decoded bit on the row —
    # an armed adversary expects casualties, so the run itself succeeds
    assert rc == 0
    assert "ERR_SNAPSHOT_TIMEOUT" in counters["errors_decoded"]
    assert counters["snapshot_lifecycle"]["failed"] > 0
    assert counters["quarantined_lanes"] > 0
    assert any("ERR_SNAPSHOT_TIMEOUT" in v
               for v in counters["lane_errors"].values())


# ---- claim 4: the snapshot_every daemon --------------------------------


def test_daemon_initiates_and_completes_without_schedule():
    cfg = dataclasses.replace(CFG, snapshot_every=6, snapshot_timeout=64,
                              snapshot_retries=2)
    runner = BatchedRunner(SPEC, cfg, FixedJaxDelay(1), batch=2,
                           scheduler="sync")
    prog = storm_program(runner.topo, phases=20, amount=1,
                         snapshot_phases={})
    final = jax.device_get(runner.run_storm(runner.init_batch(), prog))
    lc = BatchedRunner.summarize(final)["snapshot_lifecycle"]
    assert lc["initiated"] > 0
    assert lc["completed"] == lc["initiated"], lc
    assert lc["recovery_line_age_max"] >= 0        # a recovery line exists
    assert not np.any(np.asarray(final.error))


def test_graphshard_daemon_and_supervisor():
    from jax.sharding import Mesh

    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
    from chandy_lamport_tpu.utils.metrics import snapshot_lifecycle

    cfg = dataclasses.replace(
        SimConfig.for_workload(snapshots=4, max_recorded=128),
        snapshot_every=6, snapshot_timeout=64, snapshot_retries=2)
    mesh = Mesh(np.array(jax.devices()[:2]), ("graph",))
    runner = GraphShardedRunner(SPEC, cfg, mesh, seed=7, fixed_delay=1)
    prog = storm_program(runner.topo, phases=20, amount=1,
                         snapshot_phases={})
    final = jax.device_get(runner.run_storm(
        runner.init_state(), np.asarray(prog.amounts),
        np.asarray(prog.snap)))
    lc = {k: int(v) for k, v in snapshot_lifecycle(final,
                                                   runner.topo.n).items()}
    assert lc["initiated"] > 0
    assert lc["completed"] == lc["initiated"], lc
    assert int(np.asarray(final.error)) == 0


# ---- claim 5: construction contracts -----------------------------------


def test_fold_refuses_supervisor():
    with pytest.raises(ValueError, match="fold"):
        BatchedRunner(SPEC, SUP, make_fast_delay("hash", 11), batch=2,
                      scheduler="exact", exact_impl="fold")


@pytest.mark.parametrize("kw", [
    {"marker_drop_rate": -0.1}, {"marker_dup_rate": 1.5},
    {"marker_jitter_rate": 2.0},
])
def test_adversary_rejects_bad_marker_programs(kw):
    with pytest.raises(ValueError):
        JaxFaults(7, **kw)


def test_describe_carries_marker_rates():
    d = JaxFaults(7, marker_drop_rate=0.25, marker_dup_rate=0.5).describe()
    assert d["marker_drop"] == 0.25 and d["marker_dup"] == 0.5
