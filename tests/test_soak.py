"""CI wiring for the soak battery (tools/soak.py): every engine runs a
randomized sample in CI so a representation change cannot silently break an
engine the fixed-seed suites don't reach (VERDICT r4 #7 raised the volume
from 2 to 6 cases per engine). The deep battery is the tool itself
(--cases 12+ per engine)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = 6


# ~74 s on the 1-core CI box — far past the ~30 s tier-1 per-test budget
# (the 870 s wall can no longer absorb it); full passes run the battery
@pytest.mark.slow
def test_soak_all_engines():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "soak.py"),
         "--engine", "all", "--cases", str(CASES)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=1500)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    result = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert result["failed_cases"] == []
    assert result["matched"] == 3 * CASES
    assert sorted(result["engines"]) == ["exact", "shard", "sync"]
    # the randomized battery must exercise BOTH window-counter dtypes —
    # the uint16 modular-counter mode (SimConfig.window_dtype) is load-
    # bearing for the HBM footprint and must not silently fall out of
    # the randomized coverage
    assert result["window_dtypes"]["int32"] > 0
    assert result["window_dtypes"]["uint16"] > 0
