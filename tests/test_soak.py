"""Smoke wiring for the soak battery (tools/soak.py): every engine runs a
small randomized sample in CI so a representation change cannot silently
break an engine the fixed-seed suites don't reach. The deep battery is the
tool itself (--cases 12+ per engine)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_soak_all_engines_small():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "soak.py"),
         "--engine", "all", "--cases", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=900)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    result = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert result["failed_cases"] == []
    assert result["matched"] == 6
    assert sorted(result["engines"]) == ["exact", "shard", "sync"]
