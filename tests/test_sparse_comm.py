"""Sparse halo exchange: boundary-geometry coverage for the comm engines.

The differential suites (test_graphshard.py / test_graphshard_script.py)
run whatever geometry erdos_renyi and the golden fixtures happen to have;
these tests pin the corners the sparse engine's boundary tables must get
right — cut edges in both directions across a shard boundary, a zero-cut
partition (every ppermute statically elided, halo == 0), single-node
shards (P == N, the densest possible boundary), and a snapshot whose
creator's markers must reach edges owned by OTHER shards. Each case
demands bit-equality with the unsharded sync kernel after gather_dense()
reassembly, for BOTH engines, so dense stays the executable spec the
sparse path is checked against. The slow sweep at the bottom replays all
7 reference goldens through both engines.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import PassTokenEvent, SnapshotEvent, TickEvent
from chandy_lamport_tpu.models.workloads import (
    erdos_renyi,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
from chandy_lamport_tpu.ops.tick import resolve_comm_engine
from chandy_lamport_tpu.parallel.batch import BatchedRunner, compile_events
from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
from chandy_lamport_tpu.utils.fixtures import (
    TopologySpec,
    read_events_file,
    read_topology_file,
)
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path

ENGINES = ["sparse", "dense"]

# every differentially-compared DenseState field (the test_graphshard_script
# list plus the error word)
FIELDS = ("time", "tokens", "q_meta", "q_data", "q_head", "q_len",
          "tok_pushed", "mk_cnt", "m_pending", "m_rtime", "m_key",
          "next_sid", "started", "has_local", "frozen", "rem",
          "done_local", "recording", "rec_cnt", "min_prot",
          "log_amt", "rec_start", "rec_end", "completed", "error")


def _graph_mesh(p):
    return Mesh(np.array(jax.devices()[:p]), ("graph",))


def _lane0(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[0], tree)


def _ref_script(spec, script, cfg, delay=2):
    ref = BatchedRunner(spec, cfg, FixedJaxDelay(delay), batch=1,
                        scheduler="sync")
    return _lane0(jax.device_get(
        ref.run(ref.init_batch(), compile_events(ref.topo, script))))


def _assert_dense_equal(got, want, label=""):
    for name in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            err_msg=f"{label}{name}")


def _script_case(spec, script, shards, cfg=None, delay=2, **gs_kwargs):
    """Run a script unsharded and through both engines; demand equality."""
    cfg = cfg or SimConfig(queue_capacity=16, max_snapshots=8,
                           max_recorded=16)
    want = _ref_script(spec, script, cfg, delay=delay)
    runners = {}
    for engine in ENGINES:
        gs = GraphShardedRunner(spec, cfg, _graph_mesh(shards),
                                fixed_delay=delay, comm_engine=engine,
                                **gs_kwargs)
        got = gs.gather_dense(gs.run_script(gs.init_state(), script))
        _assert_dense_equal(got, want, label=f"{engine}:")
        runners[engine] = gs
    return runners


def test_cross_boundary_both_directions():
    """Tokens crossing the 2-shard boundary both ways in the same ticks:
    shard 0 owns N1/N2, shard 1 owns N3/N4; N1->N3 and N4->N2 are cut
    edges in opposite directions, so both the forward halo scatter and the
    reverse flag gather run with real (asymmetric) traffic."""
    spec = TopologySpec(
        [("N1", 6), ("N2", 4), ("N3", 5), ("N4", 3)],
        [("N1", "N3"), ("N4", "N2"), ("N1", "N2"), ("N3", "N4"),
         ("N2", "N1"), ("N4", "N3")])
    script = [
        PassTokenEvent("N1", "N3", 2), PassTokenEvent("N4", "N2", 1),
        TickEvent(1), SnapshotEvent("N1"),
        PassTokenEvent("N3", "N4", 1), PassTokenEvent("N2", "N1", 1),
        TickEvent(4), SnapshotEvent("N4"),
        PassTokenEvent("N1", "N3", 1), PassTokenEvent("N4", "N2", 2),
        TickEvent(6),
    ]
    runners = _script_case(spec, script, shards=2)
    assert runners["sparse"].halo > 0
    model = runners["sparse"].comm_model()
    assert model["cut_edges"] == 2
    assert model["sparse_bytes_per_tick"] > 0


@pytest.mark.slow
def test_zero_cut_elides_every_collective():
    """Two disconnected components, one per shard: no boundary edges, so
    the sparse engine's halo is 0 and the ppermute loops vanish
    statically — yet state must still match the unsharded run exactly
    (including the never-completing foreign-component snapshot rows)."""
    spec = TopologySpec(
        [("N1", 5), ("N2", 5), ("N3", 5), ("N4", 5)],
        [("N1", "N2"), ("N2", "N1"), ("N3", "N4"), ("N4", "N3")])
    script = [
        PassTokenEvent("N1", "N2", 2), PassTokenEvent("N3", "N4", 1),
        TickEvent(1), SnapshotEvent("N1"), SnapshotEvent("N3"),
        PassTokenEvent("N2", "N1", 1), PassTokenEvent("N4", "N3", 2),
        TickEvent(5),
    ]
    runners = _script_case(spec, script, shards=2)
    assert runners["sparse"].halo == 0
    model = runners["sparse"].comm_model()
    assert model["cut_edges"] == 0
    # only the replicated scalar reductions remain in the sparse budget
    assert (model["sparse_bytes_per_tick"]
            < model["dense_bytes_per_tick"])


def test_single_node_shards():
    """P == N (one node per shard): every edge is a cut edge and every
    neighbor block is width-1 — the densest boundary the tables express."""
    n = min(8, len(jax.devices()))
    if n < 2:
        pytest.skip("needs >= 2 devices")
    spec = erdos_renyi(n, 2.5, seed=11, tokens=60)
    cfg = SimConfig(queue_capacity=16, max_snapshots=8, max_recorded=16)
    ref = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=1,
                        scheduler="sync")
    prog = storm_program(ref.topo, phases=6, amount=1,
                         snapshot_phases=staggered_snapshots(ref.topo, 2))
    want = _lane0(jax.device_get(ref.run_storm(ref.init_batch(), prog)))
    assert int(want.error) == 0
    for engine in ENGINES:
        gs = GraphShardedRunner(spec, cfg, _graph_mesh(n), fixed_delay=2,
                                comm_engine=engine)
        assert gs.nl == 1
        got = gs.gather_dense(gs.run_storm(
            gs.init_state(), np.asarray(prog.amounts),
            np.asarray(prog.snap)))
        _assert_dense_equal(got, want, label=f"{engine}:")


@pytest.mark.slow  # ~10 s; cross-shard snapshots stay tier-1 via the sharded
# 8nodes-concurrent golden in test_graphshard_script
def test_remote_creator_marker_broadcast():
    """Snapshot initiated on shard 1 of a cross-shard ring: the creator's
    marker flags must reach the edges shard 0 owns (the reverse gather +
    dst_seg flag read), or shard 0 never starts recording for the sid."""
    spec = TopologySpec(
        [("N1", 4), ("N2", 4), ("N3", 4), ("N4", 4)],
        [("N1", "N2"), ("N2", "N3"), ("N3", "N4"), ("N4", "N1")])
    script = [
        PassTokenEvent("N1", "N2", 1), TickEvent(1),
        SnapshotEvent("N3"),           # creator on shard 1
        PassTokenEvent("N2", "N3", 1), PassTokenEvent("N4", "N1", 1),
        TickEvent(8),
    ]
    runners = _script_case(spec, script, shards=2)
    gs = runners["sparse"]
    got = gs.gather_dense(gs.run_script(gs.init_state(), script))
    assert int(got.completed[0]) == 4      # every node froze for sid 0


@pytest.mark.parametrize("megatick", [
    # K=2 costs ~14 s of compile; K=4 alone keeps the sparse-megatick
    # differential in tier-1, K=2 runs in full passes
    pytest.param(2, marks=pytest.mark.slow), 4])
def test_megatick_bit_identical(megatick):
    """K cond-gated ticks per drain dispatch must not change a single
    state bit relative to K=1, for either engine."""
    spec = erdos_renyi(16, 2.5, seed=11, tokens=80)
    cfg = SimConfig(queue_capacity=16, max_snapshots=8, max_recorded=16)
    gs1 = GraphShardedRunner(spec, cfg, _graph_mesh(4), fixed_delay=2,
                             comm_engine="sparse", megatick=1)
    prog = storm_program(gs1.topo, phases=8, amount=1,
                         snapshot_phases=staggered_snapshots(gs1.topo, 3))
    want = jax.device_get(gs1.run_storm(
        gs1.init_state(), np.asarray(prog.amounts), np.asarray(prog.snap)))
    assert int(want.error) == 0
    for engine in ENGINES:
        gsk = GraphShardedRunner(spec, cfg, _graph_mesh(4), fixed_delay=2,
                                 comm_engine=engine, megatick=megatick)
        got = jax.device_get(gsk.run_storm(
            gsk.init_state(), np.asarray(prog.amounts),
            np.asarray(prog.snap)))
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_comm_engine_knobs():
    """Config/runner validation and the auto resolution contract."""
    assert resolve_comm_engine("auto") == "sparse"
    assert resolve_comm_engine("dense") == "dense"
    assert resolve_comm_engine("sparse") == "sparse"
    with pytest.raises(ValueError):
        resolve_comm_engine("bogus")
    with pytest.raises(ValueError):
        SimConfig(comm_engine="bogus")
    spec = TopologySpec([("N1", 1), ("N2", 1)], [("N1", "N2")])
    with pytest.raises(ValueError):
        GraphShardedRunner(spec, SimConfig(), _graph_mesh(2), megatick=0)
    # SimConfig.comm_engine is the default; the kwarg overrides it
    gs = GraphShardedRunner(spec, SimConfig(comm_engine="dense"),
                            _graph_mesh(2), fixed_delay=1)
    assert gs.comm_engine == "dense"
    gs = GraphShardedRunner(spec, SimConfig(comm_engine="dense"),
                            _graph_mesh(2), fixed_delay=1,
                            comm_engine="sparse")
    assert gs.comm_engine == "sparse"


@pytest.mark.slow
@pytest.mark.parametrize("top,events,snaps", REFERENCE_TESTS,
                         ids=[t[1].removesuffix(".events")
                              for t in REFERENCE_TESTS])
@pytest.mark.parametrize("engine", ENGINES)
def test_goldens_both_engines(top, events, snaps, engine):
    """All 7 reference goldens, sharded, per engine: bit-equality with the
    unsharded sync backend (the same contract test_graphshard_script.py
    pins for the default engine on a subset)."""
    spec = read_topology_file(fixture_path(top))
    script = read_events_file(fixture_path(events))
    n = len(spec.nodes)
    shards = 2 if n % 2 == 0 else 3
    if shards > len(jax.devices()):
        pytest.skip(f"needs {shards} devices")
    cfg = SimConfig(queue_capacity=32, max_snapshots=16, max_recorded=32)
    want = _ref_script(spec, script, cfg, delay=2)
    gs = GraphShardedRunner(spec, cfg, _graph_mesh(shards), fixed_delay=2,
                            comm_engine=engine)
    got = gs.gather_dense(gs.run_script(gs.init_state(), script))
    _assert_dense_equal(got, want, label=f"{engine}:")
