"""The write-ahead admission spool (serving/spool.py): exactly-once
intake under crashes.

Host-only and jax-free on the parent side: every transaction is decided
against the replayed journal under the advisory lock, so the whole
contract — idempotent admit, lease/renew/complete, expiry redelivery,
poison quarantine, shedding, the conservation audit — is testable with
an injectable clock and no engine. The crash windows themselves are
exercised for real: subprocess children killed by SIGKILL at the named
atomicio failpoints (CLSIM_IO_FAILPOINT) inside the tmp-write/replace
and append windows, with the survivor files then re-validated strictly.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from chandy_lamport_tpu.core.spec import (
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.models.workloads import ServeRequest
from chandy_lamport_tpu.serving.spool import (
    WAL_SCHEMA_VERSION,
    AdmissionSpool,
    SpoolError,
    decode_events,
    decode_request,
    encode_events,
    encode_request,
    request_digest,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _req(job, arrival=0, tenant=0, priority=1, slack=32, tokens=2):
    return ServeRequest(
        job=job, arrival_step=arrival, tenant=tenant, priority=priority,
        deadline_step=arrival + slack,
        events=[PassTokenEvent(src="n0", dest="n1", tokens=tokens),
                SnapshotEvent(node_id="n0"), TickEvent(3)])


class TestEncoding:
    def test_event_roundtrip(self):
        evs = [PassTokenEvent(src="a", dest="b", tokens=5),
               SnapshotEvent(node_id="c"), TickEvent(7)]
        assert decode_events(encode_events(evs)) == evs

    def test_request_roundtrip(self):
        r = _req(3, arrival=9, tenant=2, priority=0)
        assert decode_request(encode_request(r)) == r

    def test_unknown_event_rows_refused(self):
        with pytest.raises(SpoolError):
            encode_events([object()])
        with pytest.raises(SpoolError):
            decode_events([["warp", 1]])

    def test_digest_is_content_pure_and_jax_free(self):
        assert request_digest(_req(0)) == request_digest(_req(0))
        assert request_digest(_req(0)) != request_digest(_req(0, tokens=3))
        # job id participates (it is the WAL's primary key)
        assert request_digest(_req(0)) != request_digest(_req(1))


class TestTransactions:
    def test_admit_ack_is_idempotent(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        assert sp.admit(_req(0)) is True
        # the crashed-ack re-send: same payload, no second record
        assert sp.admit(_req(0)) is False
        assert sp.counters()["admitted"] == 1

    def test_admit_alias_refused(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        sp.admit(_req(0))
        with pytest.raises(SpoolError, match="different digest"):
            sp.admit(_req(0, tokens=9))

    def test_lease_order_and_exactly_once_complete(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        for j, arr in ((0, 5), (1, 0), (2, 3)):
            sp.admit(_req(j, arrival=arr))
        got = sp.lease("w0", limit=2, now=100.0)
        # deterministic (arrival, job) order, not admit order
        assert [r.job for r in got] == [1, 2]
        assert sp.complete(1, "w0", {"t": 1}, now=101.0) is True
        # second commit of a terminal job is refused, not double-served
        assert sp.complete(1, "w0", {"t": 1}, now=102.0) is False
        # a worker without the lease cannot commit
        assert sp.complete(2, "w9", {"t": 2}, now=102.0) is False
        assert sp.pending() == [0]

    def test_renew_extends_only_own_live_leases(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"), lease_ttl=10.0)
        sp.admit(_req(0))
        sp.admit(_req(1))
        sp.lease("w0", limit=1, now=0.0)
        assert sp.renew("w0", [0, 1], now=5.0) == [0]
        assert sp.leases[0]["expires"] == pytest.approx(15.0)
        assert sp.renew("w1", [0], now=5.0) == []

    def test_expiry_requeue_takeover_and_late_commit(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"), lease_ttl=10.0)
        sp.admit(_req(0))
        sp.lease("w0", limit=1, now=0.0)
        # nothing expires while the heartbeat horizon holds
        assert sp.reclaim_expired(now=9.0) == {"requeued": [],
                                               "poisoned": []}
        out = sp.reclaim_expired(now=11.0)
        assert out["requeued"] == [0]
        (takeover,) = sp.lease("w1", limit=1, now=12.0)
        assert takeover.job == 0
        # the dead-but-slow original worker's late result is discarded
        assert sp.complete(0, "w0", {"t": 0}, now=13.0) is False
        assert sp.complete(0, "w1", {"t": 0}, now=13.0) is True
        assert sp.done_by[0] == "w1"
        assert sp.books["requeues"] == 1

    def test_poison_after_attempt_budget_with_provenance(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"), lease_ttl=10.0,
                            max_attempts=2)
        sp.admit(_req(0))
        sp.lease("w0", limit=1, now=0.0)
        assert sp.reclaim_expired(now=11.0)["requeued"] == [0]
        sp.lease("w1", limit=1, now=12.0)
        out = sp.reclaim_expired(now=23.0)
        assert out["poisoned"] == [0]
        trail = sp.poisoned[0]["errors"]
        assert len(trail) == 2
        assert "w0" in trail[0] and "attempt 1/2" in trail[0]
        assert "w1" in trail[1] and "attempt 2/2" in trail[1]
        assert sp.poisoned[0]["attempts"] == 2
        # terminal: not leasable, not completable
        assert sp.lease("w2", limit=1, now=24.0) == []
        assert sp.complete(0, "w1", {"t": 0}, now=24.0) is False
        assert sp.finished()

    def test_requeue_worker_is_the_fast_death_path(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"), lease_ttl=1000.0)
        sp.admit(_req(0))
        sp.admit(_req(1))
        sp.lease("w0", limit=2, now=0.0)
        out = sp.requeue_worker("w0", "worker w0 killed by SIGKILL",
                                now=1.0)
        assert out["requeued"] == [0, 1]
        assert sp.pending() == [0, 1]
        assert sp.errors[0] == ["worker w0 killed by SIGKILL"]

    def test_fail_releases_lease_and_records_provenance(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        sp.admit(_req(0))
        sp.lease("w0", limit=1, now=0.0)
        sp.fail(0, "w0", "compile exploded", now=1.0)
        assert sp.pending() == [0]
        assert sp.errors[0] == ["compile exploded"]
        # fail from a non-holder is a no-op
        sp.lease("w1", limit=1, now=2.0)
        sp.fail(0, "w0", "late report", now=3.0)
        assert sp.leases[0]["worker"] == "w1"

    def test_shed_drops_pending_only(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"))
        for j in range(3):
            sp.admit(_req(j))
        sp.lease("w0", limit=1, now=0.0)
        done = sp.shed_jobs([0, 1, 2], "backlog over capacity", now=1.0)
        assert done == [1, 2]          # job 0 is leased, never shed
        assert sp.shed == {1: "backlog over capacity",
                           2: "backlog over capacity"}

    def test_audit_conservation(self, tmp_path):
        sp = AdmissionSpool(str(tmp_path / "wal.jsonl"), lease_ttl=10.0,
                            max_attempts=1)
        for j in range(4):
            sp.admit(_req(j, arrival=j))
        sp.lease("w0", limit=1, now=0.0)
        sp.complete(0, "w0", {"t": 0}, now=1.0)
        sp.lease("w1", limit=1, now=2.0)
        sp.reclaim_expired(now=13.0)           # poisons job 1 (budget 1)
        sp.shed_jobs([3], "pressure", now=14.0)
        audit = sp.audit()
        assert audit["admitted"] == 4
        assert audit["served"] == 1 and audit["poisoned"] == 1
        assert audit["shed"] == 1 and audit["pending"] == 1
        assert audit["lost"] == 0 and audit["double_served"] == 0
        assert audit["digests_ok"]


class TestWalReplay:
    def test_rescan_is_idempotent_across_handles(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        sp = AdmissionSpool(path, lease_ttl=10.0)
        for j in range(3):
            sp.admit(_req(j, arrival=j))
        sp.lease("w0", limit=1, now=0.0)
        sp.complete(0, "w0", {"tokens": [1, 2]}, now=1.0)
        size = os.path.getsize(path)
        for _ in range(3):                     # fresh crash-restart scans
            fresh = AdmissionSpool(path, lease_ttl=10.0)
            assert fresh.done == {0: {"tokens": [1, 2]}}
            assert fresh.pending() == [1, 2]
            assert fresh.books["torn_tail_truncated"] == 0
        # replay never rewrites history
        assert os.path.getsize(path) == size

    def test_cross_handle_visibility(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        a = AdmissionSpool(path)
        b = AdmissionSpool(path)
        a.admit(_req(0))
        # b's next transaction replays a's append before deciding
        assert b.lease("wb", limit=1, now=0.0)[0].job == 0

    def test_torn_tail_truncated_and_skipped(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        sp = AdmissionSpool(path)
        sp.admit(_req(0))
        size = os.path.getsize(path)
        with open(path, "ab") as f:
            f.write(b'{"wal_schema": 1, "kind": "admit", "jo')
        fresh = AdmissionSpool(path)
        assert fresh.books["torn_tail_truncated"] == 1
        assert list(fresh.requests) == [0]
        # the torn bytes are gone — the next append lands on a boundary
        assert os.path.getsize(path) == size
        assert fresh.admit(_req(1)) is True
        again = AdmissionSpool(path)
        assert sorted(again.requests) == [0, 1]
        assert again.books["torn_tail_truncated"] == 0

    def test_mid_file_damage_is_refused(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        sp = AdmissionSpool(path)
        sp.admit(_req(0))
        rec = {"wal_schema": WAL_SCHEMA_VERSION, "kind": "shed",
               "job": 0, "reason": "x", "t": 1.0}
        with open(path, "ab") as f:
            f.write(b"@@not json@@\n")         # complete line, mid-file
            f.write((json.dumps(rec) + "\n").encode())
        # a newline-terminated unparsable line is NOT a torn append —
        # refuse loudly rather than skip it (it could be a lost record)
        with pytest.raises(SpoolError, match="corrupt record at byte"):
            AdmissionSpool(path)
        # the already-open handle hits it on its next transaction too
        with pytest.raises(SpoolError, match="corrupt record at byte"):
            sp.admit(_req(1))

    def test_stale_schema_is_refused_by_name(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        rec = {"wal_schema": WAL_SCHEMA_VERSION + 1, "kind": "admit",
               "job": 0, "digest": "0" * 64,
               "request": encode_request(_req(0)), "t": 0.0}
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
        with pytest.raises(SpoolError) as err:
            AdmissionSpool(path)
        assert "wal_schema" in str(err.value)
        assert path in str(err.value)

    def test_double_done_is_structurally_refused(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        sp = AdmissionSpool(path)
        sp.admit(_req(0))
        sp.lease("w0", limit=1, now=0.0)
        sp.complete(0, "w0", {"t": 0}, now=1.0)
        done = {"wal_schema": WAL_SCHEMA_VERSION, "kind": "done",
                "job": 0, "worker": "w1", "summary": {"t": 0}, "t": 2.0}
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(done) + "\n")
        with pytest.raises(SpoolError, match="double-serve"):
            AdmissionSpool(path)

    def test_record_for_unknown_job_refused(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        rec = {"wal_schema": WAL_SCHEMA_VERSION, "kind": "lease",
               "job": 7, "worker": "w0", "expires": 1.0, "attempt": 1,
               "t": 0.0}
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
        with pytest.raises(SpoolError, match="never admitted"):
            AdmissionSpool(path)


def _run_child(code: str, failpoint: str) -> subprocess.CompletedProcess:
    env = {**os.environ, "CLSIM_IO_FAILPOINT": failpoint}
    return subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                          env=env, stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, timeout=60)


class TestKillInTheWindow:
    """SIGKILL inside the durable-write windows (utils/atomicio
    failpoints): the survivor file must be the complete OLD state, and
    the next writer must succeed — the crash window between tmp-write
    and os.replace leaks nothing but a stale tmp file."""

    def test_memocache_kill_between_tmp_and_replace(self, tmp_path):
        path = str(tmp_path / "memo.jsonl")
        from chandy_lamport_tpu.utils.memocache import SummaryCache
        old = SummaryCache(path)
        old.put("a" * 64, {"tokens": [1]})
        old.flush()
        before = open(path, encoding="utf-8").read()
        proc = _run_child(f"""
            import sys
            sys.path.insert(0, {ROOT!r})
            from chandy_lamport_tpu.utils.memocache import SummaryCache
            c = SummaryCache({path!r})
            c.put("b" * 64, {{"tokens": [2]}})
            c.flush()
            raise SystemExit(3)   # unreachable: the failpoint kills us
        """, "memocache-replace")
        assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
        # the published name still carries the complete old state...
        assert open(path, encoding="utf-8").read() == before
        # ...while the orphan tmp proves the kill landed inside the
        # window (bytes written and fsynced, name never committed)
        assert os.path.exists(path + ".tmp")
        assert '"b' in open(path + ".tmp", encoding="utf-8").read()
        # and the next writer recovers: strict load + merge + replace
        nxt = SummaryCache(path)
        assert nxt.get("a" * 64) == {"tokens": [1]}
        assert nxt.get("b" * 64) is None
        nxt.put("c" * 64, {"tokens": [3]})
        nxt.flush()
        assert SummaryCache(path).get("c" * 64) == {"tokens": [3]}

    def test_spool_kill_before_append_never_acks(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        sp = AdmissionSpool(path)
        sp.admit(_req(0))
        size = os.path.getsize(path)
        proc = _run_child(f"""
            import sys
            sys.path.insert(0, {ROOT!r})
            from chandy_lamport_tpu.serving.spool import AdmissionSpool
            from chandy_lamport_tpu.models.workloads import ServeRequest
            from chandy_lamport_tpu.core.spec import TickEvent
            sp = AdmissionSpool({path!r})
            sp.admit(ServeRequest(job=1, arrival_step=0, tenant=0,
                                  priority=1, deadline_step=32,
                                  events=[TickEvent(3)]))
            raise SystemExit(3)   # unreachable
        """, "spool-append")
        assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
        # the ack never returned, so the journal must not carry job 1 —
        # and must still be on a record boundary
        assert os.path.getsize(path) == size
        fresh = AdmissionSpool(path)
        assert list(fresh.requests) == [0]
        assert fresh.books["torn_tail_truncated"] == 0
        # the caller's contract: retry the admit; it lands cleanly
        assert fresh.admit(_req(1)) is True

    def test_checkpoint_kill_between_tmp_and_replace(self, tmp_path):
        path = str(tmp_path / "state.ckpt")
        proc = _run_child(f"""
            import os, sys
            sys.path.insert(0, {ROOT!r})
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            from chandy_lamport_tpu.config import SimConfig
            from chandy_lamport_tpu.core.state import (DenseTopology,
                                                       init_state)
            from chandy_lamport_tpu.models.workloads import ring_topology
            from chandy_lamport_tpu.utils import checkpoint
            topo = DenseTopology(ring_topology(4))
            state = init_state(topo, SimConfig(), None)
            checkpoint.save_state({path!r}, state)
            raise SystemExit(3)   # unreachable
        """, "checkpoint-replace")
        assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
        # the name was never committed: no torn checkpoint to mis-load
        assert not os.path.exists(path)
        assert os.path.exists(path + ".tmp")
