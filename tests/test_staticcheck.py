"""tools/staticcheck: the AST lint plane and the fingerprint registry.

The AST tests are jax-free and near-instant; the jaxpr plane traces real
entries and is marked slow (the tier-1 gate runs -m 'not slow').
"""

import json
import os

import pytest

from tools.staticcheck import apply_allowlist
from tools.staticcheck import ast_lint
from tools.staticcheck.jaxpr_audit import load_registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow  # ~8 s full-tree sweep; the per-rule unit tests below stay
# tier-1 and `python -m tools.staticcheck --plane ast` runs in full passes
def test_ast_plane_clean_on_tree():
    # the shipped tree must satisfy its own structural invariants
    # (err-bit registry, knob pattern, ckpt history, scatter modes)
    kept, _allowed = apply_allowlist(ast_lint.lint_tree(REPO_ROOT))
    assert kept == [], [v.to_dict() for v in kept]


def test_err_bit_registry_rejects_non_power_of_two():
    sources = {ast_lint.STATE_PATH: (
        "ERR_A = 1\n"
        "ERR_B = 3\n"
        "ERROR_REGISTRY = ((\"ERR_A\", ERR_A, \"a\"), (\"ERR_B\", ERR_B, \"b\"))\n"
        "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
        "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
        "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
    )}
    vs = ast_lint.check_error_bits(sources)
    assert any("not a power of two" in v.detail for v in vs), \
        [v.detail for v in vs]


def test_err_bit_registry_accepts_tuple_and_call_rows():
    # rows as bare tuples and as constructor calls must both parse
    for row_b in ("(\"ERR_B\", ERR_B, \"b\")", "ErrorBit(\"ERR_B\", ERR_B, \"b\")"):
        sources = {ast_lint.STATE_PATH: (
            "ERR_A = 1\n"
            "ERR_B = 2\n"
            "ERROR_REGISTRY = ((\"ERR_A\", ERR_A, \"a\"), " + row_b + ")\n"
            "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
            "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
            "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
        )}
        assert ast_lint.check_error_bits(sources) == []


def test_err_bit_registry_catches_name_bit_mismatch():
    sources = {ast_lint.STATE_PATH: (
        "ERR_A = 1\n"
        "ERR_B = 2\n"
        "ERROR_REGISTRY = ((\"ERR_A\", ERR_B, \"a\"), (\"ERR_B\", ERR_B, \"b\"))\n"
        "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
        "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
        "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
    )}
    vs = ast_lint.check_error_bits(sources)
    assert any("name and bit disagree" in v.detail for v in vs), \
        [v.detail for v in vs]


def test_ckpt_history_rejects_version_gap():
    # a v8 row appended as v9 (or any gap) breaks the consecutive-from-1
    # contract the supported-range error message relies on
    sources = {ast_lint.STATE_PATH: (
        "CHECKPOINT_FORMAT_HISTORY = (\n"
        "    (1, \"genesis\"),\n"
        "    (2, \"quarantine\"),\n"
        "    (9, \"memo plane\"),\n"
        ")\n"
        "CHECKPOINT_FORMAT_VERSION = CHECKPOINT_FORMAT_HISTORY[-1][0]\n"
    )}
    vs = ast_lint.check_ckpt_versions(sources)
    assert any(v.rule == "ckpt-history" and "expected 3" in v.detail
               for v in vs), [v.detail for v in vs]


_MEMO_KNOB_OK = (
    "ENGINE_KNOBS = {\n"
    "    \"memo\": (\"off\", \"admit\", \"full\", \"prefix\"),\n"
    "}\n"
)
_RESOLVE_MEMO_OK = (
    "from chandy_lamport_tpu.config import ENGINE_KNOBS\n"
    "def resolve_memo(memo):\n"
    "    if memo not in ENGINE_KNOBS[\"memo\"]:\n"
    "        raise ValueError(memo)\n"
    "    return memo\n"
)


def test_memo_knob_requires_table_row_and_ladder_order():
    # missing row
    vs = ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH: "ENGINE_KNOBS = {\"scheduler\": (\"sync\",)}\n",
        "chandy_lamport_tpu/utils/memocache.py": _RESOLVE_MEMO_OK})
    assert any("no 'memo' row" in v.detail for v in vs), \
        [v.detail for v in vs]
    # row present but ladder reordered: off must lead
    vs = ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH:
            "ENGINE_KNOBS = {\"memo\": (\"full\", \"admit\", \"off\")}\n",
        "chandy_lamport_tpu/utils/memocache.py": _RESOLVE_MEMO_OK})
    assert any("'off' leads" in v.detail for v in vs), [v.detail for v in vs]
    # the clean shape passes
    assert ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH: _MEMO_KNOB_OK,
        "chandy_lamport_tpu/utils/memocache.py": _RESOLVE_MEMO_OK}) == []


def test_memo_knob_rejects_inline_spelling_copy():
    bad_resolver = (
        "def resolve_memo(memo):\n"
        "    if memo not in (\"off\", \"admit\", \"full\"):\n"
        "        raise ValueError(memo)\n"
        "    return memo\n"
    )
    vs = ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH: _MEMO_KNOB_OK,
        "chandy_lamport_tpu/utils/memocache.py": bad_resolver})
    details = [v.detail for v in vs]
    assert any("does not consult ENGINE_KNOBS" in d for d in details), details
    assert any("restates the memo spellings inline" in d
               for d in details), details


def test_memo_schema_single_named_constant():
    # restated literal in a schema-stamping dict
    vs = ast_lint.check_memo_schema({ast_lint.MEMOCACHE_PATH: (
        "MEMOCACHE_SCHEMA_VERSION = 1\n"
        "def put():\n"
        "    return {\"schema\": 1, \"digest\": \"d\"}\n"
    )})
    assert any("restated literal 1" in v.detail for v in vs), \
        [v.detail for v in vs]
    # re-assignment outside memocache.py
    vs = ast_lint.check_memo_schema({
        ast_lint.MEMOCACHE_PATH: "MEMOCACHE_SCHEMA_VERSION = 1\n",
        "chandy_lamport_tpu/parallel/batch.py":
            "MEMOCACHE_SCHEMA_VERSION = 2\n"})
    assert any("lives only in utils/memocache.py" in v.detail
               for v in vs), [v.detail for v in vs]
    # the clean shape (Name reference at the stamp site) passes
    assert ast_lint.check_memo_schema({ast_lint.MEMOCACHE_PATH: (
        "MEMOCACHE_SCHEMA_VERSION = 1\n"
        "def put():\n"
        "    return {\"schema\": MEMOCACHE_SCHEMA_VERSION}\n"
    )}) == []


_PREFIX_CACHE_OK = (
    "PREFIXCACHE_SCHEMA_VERSION = 1\n"
    "class PrefixCache:\n"
    "    def flush(self):\n"
    "        with locked(self.path):\n"
    "            with open(self.path + \".tmp\", \"w\") as f:\n"
    "                f.write(\"x\")\n"
    "    def line(self, digest, entry):\n"
    "        return {\"schema\": PREFIXCACHE_SCHEMA_VERSION,\n"
    "                \"digest\": digest, \"depth\": entry[\"depth\"],\n"
    "                \"seen\": 0, \"ckpt\": None}\n"
)


def test_prefix_schema_single_named_constant():
    # restated literal in a prefix ENTRY dict (depth/ckpt shape)
    vs = ast_lint.check_prefix_schema({ast_lint.MEMOCACHE_PATH: (
        "PREFIXCACHE_SCHEMA_VERSION = 1\n"
        "class PrefixCache:\n"
        "    def line(self):\n"
        "        return {\"schema\": 1, \"digest\": \"d\", \"depth\": 2,\n"
        "                \"seen\": 0, \"ckpt\": None}\n"
    )})
    assert any("other than the PREFIXCACHE_SCHEMA_VERSION Name" in v.detail
               for v in vs), [v.detail for v in vs]
    # re-assignment outside memocache.py
    vs = ast_lint.check_prefix_schema({
        ast_lint.MEMOCACHE_PATH: _PREFIX_CACHE_OK,
        "chandy_lamport_tpu/parallel/batch.py":
            "PREFIXCACHE_SCHEMA_VERSION = 2\n"})
    assert any("lives only in utils/memocache.py" in v.detail
               for v in vs), [v.detail for v in vs]
    # a memo SUMMARY line (no depth/ckpt keys) is the other plane's
    # business — this rule must not claim it
    vs = ast_lint.check_prefix_schema({ast_lint.MEMOCACHE_PATH: (
        "PREFIXCACHE_SCHEMA_VERSION = 1\n"
        "class PrefixCache:\n"
        "    pass\n"
        "def memo_line():\n"
        "    return {\"schema\": 1, \"digest\": \"d\"}\n"
    )})
    assert vs == [], [v.detail for v in vs]


def test_prefix_schema_requires_locked_writes():
    # write-mode open inside PrefixCache but OUTSIDE `with locked(...)`
    vs = ast_lint.check_prefix_schema({ast_lint.MEMOCACHE_PATH: (
        "PREFIXCACHE_SCHEMA_VERSION = 1\n"
        "class PrefixCache:\n"
        "    def flush(self):\n"
        "        with open(self.path, \"w\") as f:\n"
        "            f.write(\"x\")\n"
    )})
    assert any("outside a `with locked(...)` block" in v.detail
               for v in vs), [v.detail for v in vs]
    # read-mode opens are fine unlocked; locked writes are fine
    assert ast_lint.check_prefix_schema({
        ast_lint.MEMOCACHE_PATH: _PREFIX_CACHE_OK}) == []
    # the REAL tree holds the discipline
    assert [v for v in ast_lint.lint_tree(REPO_ROOT)
            if v.rule == "prefix-schema"] == []


_SERVE_KNOB_OK = (
    "ENGINE_KNOBS = {\n"
    "    \"memo\": (\"off\", \"admit\", \"full\", \"prefix\"),\n"
    "    \"serve_policy\": (\"edf\", \"fifo\"),\n"
    "}\n"
)
_RESOLVE_SERVE_OK = (
    "from chandy_lamport_tpu.config import ENGINE_KNOBS\n"
    "def resolve_serve_policy(policy):\n"
    "    if policy not in ENGINE_KNOBS[\"serve_policy\"]:\n"
    "        raise ValueError(policy)\n"
    "    return policy\n"
)


def test_serve_knob_requires_table_row_and_default_order():
    # missing row
    vs = ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH: "ENGINE_KNOBS = {\"memo\": (\"off\",)}\n",
        "chandy_lamport_tpu/serving/admission.py": _RESOLVE_SERVE_OK})
    assert any("no 'serve_policy' row" in v.detail for v in vs), \
        [v.detail for v in vs]
    # row present but reordered: edf (the default) must lead
    vs = ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH:
            "ENGINE_KNOBS = {\"serve_policy\": (\"fifo\", \"edf\")}\n",
        "chandy_lamport_tpu/serving/admission.py": _RESOLVE_SERVE_OK})
    assert any("'edf' leads" in v.detail for v in vs), [v.detail for v in vs]
    # the clean shape passes
    assert ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH: _SERVE_KNOB_OK,
        "chandy_lamport_tpu/serving/admission.py": _RESOLVE_SERVE_OK}) == []


def test_serve_knob_rejects_inline_spelling_copy():
    bad_resolver = (
        "def resolve_serve_policy(policy):\n"
        "    if policy not in (\"edf\", \"fifo\"):\n"
        "        raise ValueError(policy)\n"
        "    return policy\n"
    )
    vs = ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH: _SERVE_KNOB_OK,
        "chandy_lamport_tpu/serving/admission.py": bad_resolver})
    details = [v.detail for v in vs]
    assert any("does not consult ENGINE_KNOBS" in d for d in details), details
    assert any("restates the policy spellings inline" in d
               for d in details), details


def test_serve_schema_single_named_constant():
    # restated literal at a serve_schema stamp site
    vs = ast_lint.check_serve_schema({ast_lint.SERVING_SERVER_PATH: (
        "SERVE_SCHEMA_VERSION = 1\n"
        "def row():\n"
        "    return {\"serve_schema\": 1, \"kind\": \"serve_interval\"}\n"
    )})
    assert any("restated literal 1" in v.detail for v in vs), \
        [v.detail for v in vs]
    # re-assignment outside serving/server.py
    vs = ast_lint.check_serve_schema({
        ast_lint.SERVING_SERVER_PATH: "SERVE_SCHEMA_VERSION = 1\n",
        "chandy_lamport_tpu/cli.py": "SERVE_SCHEMA_VERSION = 2\n"})
    assert any("lives only in serving/server.py" in v.detail
               for v in vs), [v.detail for v in vs]
    # the clean shape (Name reference at the stamp site) passes
    assert ast_lint.check_serve_schema({ast_lint.SERVING_SERVER_PATH: (
        "SERVE_SCHEMA_VERSION = 1\n"
        "def row():\n"
        "    return {\"serve_schema\": SERVE_SCHEMA_VERSION}\n"
    )}) == []


def test_registry_loader_reads_legacy_and_schema2(tmp_path):
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"k": "abc"}))
    entries, ver = load_registry(str(legacy))
    assert entries == {"k": "abc"} and ver is None

    v2 = tmp_path / "v2.json"
    v2.write_text(json.dumps(
        {"schema": 2, "jax": "9.9.9", "entries": {"k": "def"}}))
    entries, ver = load_registry(str(v2))
    assert entries == {"k": "def"} and ver == "9.9.9"

    missing, ver = load_registry(str(tmp_path / "nope.json"))
    assert missing == {} and ver is None


def test_shipped_registry_is_schema2_and_version_stamped():
    entries, ver = load_registry()
    assert entries, "fingerprints.json has no entries"
    assert ver, "fingerprints.json does not record the jax version"


@pytest.mark.slow
def test_jaxpr_fast_plane_clean():
    from tools.staticcheck import jaxpr_audit
    vs, audited, _ = jaxpr_audit.audit("fast", check_fingerprints=True)
    kept, _allowed = apply_allowlist(vs)
    assert kept == [], [v.to_dict() for v in kept]
    assert len(audited) == 6


def test_host_sync_flags_item_float_and_carry_asarray():
    src = (
        "import numpy as np\n"
        "def step(state):\n"
        "    a = state.time.item()\n"
        "    b = float(state.time)\n"
        "    c = np.asarray(state.tokens)\n"
        "    d = np.asarray(amounts)\n"      # non-carry root: fine
        "    e = float(\"1.5\")\n"           # literal: fine
        "    return a, b, c, d, e\n"
    )
    vs = ast_lint.check_host_sync({"chandy_lamport_tpu/ops/foo.py": src})
    assert [v.rule for v in vs] == ["host-sync"] * 3, \
        [v.to_dict() for v in vs]
    assert {v.where.split(":")[1] for v in vs} == {"3", "4", "5"}
    # the same source outside ops/kernels/parallel is not scanned
    assert ast_lint.check_host_sync(
        {"chandy_lamport_tpu/utils/foo.py": src}) == []


def test_host_sync_allowlists_declared_sites_per_function():
    src = (
        "import numpy as np\n"
        "def pack_jobs(s):\n"            # declared host-side site
        "    return np.asarray(s.tokens)\n"
        "def step(s):\n"                 # NOT declared -> flagged
        "    return np.asarray(s.tokens)\n"
    )
    vs = ast_lint.check_host_sync({ast_lint.BATCH_PATH: src})
    assert len(vs) == 1 and vs[0].where.endswith(":5"), \
        [v.to_dict() for v in vs]
    # module-level host code (import-time constants) is out of scope
    assert ast_lint.check_host_sync({
        "chandy_lamport_tpu/ops/foo.py":
            "import numpy as np\nx = np.asarray(state)\n"}) == []


def test_cache_lock_requires_locked_replace():
    bad = (
        "import os\n"
        "def flush(path, tmp):\n"
        "    os.replace(tmp, path)\n"
    )
    vs = ast_lint.check_cache_lock({ast_lint.MEMOCACHE_PATH: bad})
    assert len(vs) == 1 and vs[0].rule == "cache-lock" and \
        vs[0].where.endswith(":3"), [v.to_dict() for v in vs]
    good = (
        "import os\n"
        "from chandy_lamport_tpu.utils.filelock import locked\n"
        "def flush(path, tmp):\n"
        "    with locked(path):\n"
        "        os.replace(tmp, path)\n"
    )
    assert ast_lint.check_cache_lock({ast_lint.SERVING_EXEC_PATH: good}) == []
    # files outside the shared-cache set are not this rule's business
    assert ast_lint.check_cache_lock({
        "chandy_lamport_tpu/utils/checkpoint.py": bad}) == []


def test_wal_append_bans_rewrites_and_unlocked_journal_io():
    bad = (
        "import os\n"
        "from chandy_lamport_tpu.utils.atomicio import fsync_append\n"
        "def commit(path, tmp, line):\n"
        "    os.replace(tmp, path)\n"
        "    with open(path, 'wb') as f:\n"
        "        f.write(line)\n"
        "def append(path, line):\n"
        "    with open(path, 'ab') as f:\n"
        "        fsync_append(f, line)\n"
        "def repair(path, off):\n"
        "    os.truncate(path, off)\n"
    )
    vs = ast_lint.check_wal_append({ast_lint.SPOOL_PATH: bad})
    assert [v.rule for v in vs] == ["wal-append"] * 5, \
        [v.to_dict() for v in vs]
    # the rename, the write-mode open, the raw write, the unlocked
    # append and the unlocked torn-tail truncate — each named by line
    assert {v.where.split(":")[1] for v in vs} == {"4", "5", "6", "9", "11"}
    # other files are not this rule's business
    assert ast_lint.check_wal_append({
        "chandy_lamport_tpu/utils/checkpoint.py": bad}) == []


def test_wal_append_accepts_the_locked_helper_discipline():
    # the real spool's shape: private mutators touch the journal, their
    # callers hold the lock — legal in both directions
    good = (
        "import os\n"
        "from chandy_lamport_tpu.utils.atomicio import fsync_append\n"
        "from chandy_lamport_tpu.utils.filelock import locked\n"
        "class Spool:\n"
        "    def _append(self, line):\n"
        "        with open(self.path, 'ab') as f:\n"
        "            fsync_append(f, line)\n"
        "    def _replay(self):\n"
        "        os.truncate(self.path, 0)\n"
        "    def admit(self, line):\n"
        "        with locked(self.path):\n"
        "            self._replay()\n"
        "            self._append(line)\n"
    )
    assert ast_lint.check_wal_append({ast_lint.SPOOL_PATH: good}) == []
    # ... but calling a lock-holding helper WITHOUT the lock is flagged
    naked = (
        "class Spool:\n"
        "    def peek(self):\n"
        "        self._replay()\n"
    )
    vs = ast_lint.check_wal_append({ast_lint.SPOOL_PATH: naked})
    assert len(vs) == 1 and "_replay" in vs[0].detail and \
        vs[0].where.endswith(":3"), [v.to_dict() for v in vs]


def test_wal_append_fsync_helper_must_actually_fsync():
    lazy = (
        "def fsync_append(f, data):\n"
        "    f.write(data)\n"
        "    f.flush()\n"
        "    return len(data)\n"
    )
    vs = ast_lint.check_wal_append({ast_lint.SPOOL_PATH: "x = 1\n",
                                    ast_lint.ATOMICIO_PATH: lazy})
    assert len(vs) == 1 and "os.fsync" in vs[0].detail, \
        [v.to_dict() for v in vs]
    good = (
        "import os\n"
        "def fsync_append(f, data):\n"
        "    f.write(data)\n"
        "    f.flush()\n"
        "    os.fsync(f.fileno())\n"
        "    return len(data)\n"
    )
    assert ast_lint.check_wal_append({ast_lint.SPOOL_PATH: "x = 1\n",
                                      ast_lint.ATOMICIO_PATH: good}) == []


def test_cost_budget_ceiling_semantics():
    from tools.staticcheck.hlo_cost import check_against_budget

    # missing budget is itself a violation naming the regenerate knob
    vs = check_against_budget("arm", {"flops": 1.0}, None)
    assert len(vs) == 1 and "--budgets-update" in vs[0].detail
    # floats get FLOAT_TOL headroom; counts are exact ceilings
    assert check_against_budget(
        "arm", {"flops": 100.5}, {"flops": 100.0}) == []
    vs = check_against_budget(
        "arm", {"flops": 150.0, "collective_count": 2},
        {"flops": 100.0, "collective_count": 1})
    details = " | ".join(v.detail for v in vs)
    assert "flops regressed" in details
    assert "collective_count regressed" in details
    # under budget is an improvement, never a violation
    assert check_against_budget(
        "arm", {"flops": 10.0, "collective_count": 0},
        {"flops": 100.0, "collective_count": 1}) == []
    # a metric the registry predates cannot fail retroactively
    assert check_against_budget("arm", {"new_metric": 9.0}, {}) == []


def test_cost_budget_registry_roundtrip(tmp_path):
    import jax

    from tools.staticcheck import hlo_cost

    path = str(tmp_path / "budgets.json")
    entries = {"arm.x": {"flops": 10.0, "collective_count": 1}}
    hlo_cost.save_budgets(entries, path)
    loaded, ver = hlo_cost.load_budgets(path)
    assert loaded == entries and ver == jax.__version__
    # a foreign-schema file is rejected loudly, never half-read
    (tmp_path / "bad.json").write_text(
        json.dumps({"schema": 99, "entries": {}}))
    with pytest.raises(ValueError, match="schema 99"):
        hlo_cost.load_budgets(str(tmp_path / "bad.json"))
    missing, ver = hlo_cost.load_budgets(str(tmp_path / "nope.json"))
    assert missing == {} and ver is None


def test_shipped_cost_budgets_cover_the_matrix():
    from tools.staticcheck.hlo_cost import load_budgets

    entries, ver = load_budgets()
    assert len(entries) >= 60, "cost_budgets.json must pin every arm"
    assert ver, "cost_budgets.json does not record the jax version"


def test_cost_plane_names_an_injected_collective():
    # the deliberate-regression drill: the same computation with one
    # extra psum must fail its budget with the collective metrics NAMED
    import jax
    import jax.numpy as jnp

    from tools.staticcheck.hlo_cost import (
        check_against_budget,
        measure_compiled,
    )

    n = jax.device_count()
    x = jnp.zeros((n, 8), jnp.float32)
    clean = measure_compiled(
        jax.pmap(lambda v: v * 2, axis_name="i").lower(x).compile())
    regressed = measure_compiled(
        jax.pmap(lambda v: jax.lax.psum(v * 2, "i"),
                 axis_name="i").lower(x).compile())
    assert clean["collective_count"] == 0
    vs = check_against_budget("scratch.psum", regressed, clean)
    details = " | ".join(v.detail for v in vs)
    assert "all_reduce_count regressed" in details, details
    assert "collective_count regressed" in details, details
    # and the injected arm passes against its own ceiling
    assert check_against_budget("scratch.psum", regressed, regressed) == []


def test_hlo_op_stats_counts_defs_not_operands():
    from tools.staticcheck.hlo_cost import hlo_op_stats

    hlo = (
        "  %ag = f32[8,16]{1,0} all-gather(f32[8,2]{1,0} %p0)\n"
        "  %ar.1 = f32[8]{0} all-reduce-start(f32[8]{0} %p1)\n"
        "  %ar.2 = f32[8]{0} all-reduce-done(f32[8]{0} %ar.1)\n"
        "  %g = s32[4]{0} gather(s32[8]{0} %p2, s32[4]{0} %idx)\n"
        "  %f = (f32[2]{0}, s32[2]{0}) fusion(f32[8]{0} %p3)\n"
    )
    row = hlo_op_stats(hlo)
    assert row["all_gather_count"] == 1
    assert row["all_reduce_count"] == 1     # -start counts, -done doesn't
    assert row["gather_count"] == 1 and row["fusion_count"] == 1
    assert row["collective_count"] == 2
    # bytes: all-gather f32[8,16] = 512, all-reduce f32[8] = 32
    assert row["collective_bytes"] == 512 + 32


@pytest.mark.slow
def test_runtime_sentry_stream_steady_state_is_silent():
    # zero retraces, zero un-allowlisted transfers per steady-state
    # stream step after warmup (the tentpole's runtime contract).
    # slow: `python -m tools.staticcheck --plane runtime` enforces the
    # same contract across all 9 knob rows out-of-band of the gate.
    from tools.staticcheck import runtime_sentry

    vs, steps = runtime_sentry._stream_row(
        "stream.sync.memo=off", "sync", "off")
    assert vs == [], [v.to_dict() for v in vs]
    assert steps > 0


@pytest.mark.slow
def test_runtime_sentry_serve_steady_state_is_silent():
    from tools.staticcheck import runtime_sentry

    vs, steps = runtime_sentry._serve_row("serve.policy=edf", "edf")
    assert vs == [], [v.to_dict() for v in vs]
    assert steps > 0
