"""tools/staticcheck: the AST lint plane and the fingerprint registry.

The AST tests are jax-free and near-instant; the jaxpr plane traces real
entries and is marked slow (the tier-1 gate runs -m 'not slow').
"""

import json
import os

import pytest

from tools.staticcheck import apply_allowlist
from tools.staticcheck import ast_lint
from tools.staticcheck.jaxpr_audit import load_registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ast_plane_clean_on_tree():
    # the shipped tree must satisfy its own structural invariants
    # (err-bit registry, knob pattern, ckpt history, scatter modes)
    kept, _allowed = apply_allowlist(ast_lint.lint_tree(REPO_ROOT))
    assert kept == [], [v.to_dict() for v in kept]


def test_err_bit_registry_rejects_non_power_of_two():
    sources = {ast_lint.STATE_PATH: (
        "ERR_A = 1\n"
        "ERR_B = 3\n"
        "ERROR_REGISTRY = ((\"ERR_A\", ERR_A, \"a\"), (\"ERR_B\", ERR_B, \"b\"))\n"
        "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
        "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
        "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
    )}
    vs = ast_lint.check_error_bits(sources)
    assert any("not a power of two" in v.detail for v in vs), \
        [v.detail for v in vs]


def test_err_bit_registry_accepts_tuple_and_call_rows():
    # rows as bare tuples and as constructor calls must both parse
    for row_b in ("(\"ERR_B\", ERR_B, \"b\")", "ErrorBit(\"ERR_B\", ERR_B, \"b\")"):
        sources = {ast_lint.STATE_PATH: (
            "ERR_A = 1\n"
            "ERR_B = 2\n"
            "ERROR_REGISTRY = ((\"ERR_A\", ERR_A, \"a\"), " + row_b + ")\n"
            "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
            "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
            "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
        )}
        assert ast_lint.check_error_bits(sources) == []


def test_err_bit_registry_catches_name_bit_mismatch():
    sources = {ast_lint.STATE_PATH: (
        "ERR_A = 1\n"
        "ERR_B = 2\n"
        "ERROR_REGISTRY = ((\"ERR_A\", ERR_B, \"a\"), (\"ERR_B\", ERR_B, \"b\"))\n"
        "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
        "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
        "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
    )}
    vs = ast_lint.check_error_bits(sources)
    assert any("name and bit disagree" in v.detail for v in vs), \
        [v.detail for v in vs]


def test_ckpt_history_rejects_version_gap():
    # a v8 row appended as v9 (or any gap) breaks the consecutive-from-1
    # contract the supported-range error message relies on
    sources = {ast_lint.STATE_PATH: (
        "CHECKPOINT_FORMAT_HISTORY = (\n"
        "    (1, \"genesis\"),\n"
        "    (2, \"quarantine\"),\n"
        "    (9, \"memo plane\"),\n"
        ")\n"
        "CHECKPOINT_FORMAT_VERSION = CHECKPOINT_FORMAT_HISTORY[-1][0]\n"
    )}
    vs = ast_lint.check_ckpt_versions(sources)
    assert any(v.rule == "ckpt-history" and "expected 3" in v.detail
               for v in vs), [v.detail for v in vs]


_MEMO_KNOB_OK = (
    "ENGINE_KNOBS = {\n"
    "    \"memo\": (\"off\", \"admit\", \"full\"),\n"
    "}\n"
)
_RESOLVE_MEMO_OK = (
    "from chandy_lamport_tpu.config import ENGINE_KNOBS\n"
    "def resolve_memo(memo):\n"
    "    if memo not in ENGINE_KNOBS[\"memo\"]:\n"
    "        raise ValueError(memo)\n"
    "    return memo\n"
)


def test_memo_knob_requires_table_row_and_ladder_order():
    # missing row
    vs = ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH: "ENGINE_KNOBS = {\"scheduler\": (\"sync\",)}\n",
        "chandy_lamport_tpu/utils/memocache.py": _RESOLVE_MEMO_OK})
    assert any("no 'memo' row" in v.detail for v in vs), \
        [v.detail for v in vs]
    # row present but ladder reordered: off must lead
    vs = ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH:
            "ENGINE_KNOBS = {\"memo\": (\"full\", \"admit\", \"off\")}\n",
        "chandy_lamport_tpu/utils/memocache.py": _RESOLVE_MEMO_OK})
    assert any("'off' leads" in v.detail for v in vs), [v.detail for v in vs]
    # the clean shape passes
    assert ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH: _MEMO_KNOB_OK,
        "chandy_lamport_tpu/utils/memocache.py": _RESOLVE_MEMO_OK}) == []


def test_memo_knob_rejects_inline_spelling_copy():
    bad_resolver = (
        "def resolve_memo(memo):\n"
        "    if memo not in (\"off\", \"admit\", \"full\"):\n"
        "        raise ValueError(memo)\n"
        "    return memo\n"
    )
    vs = ast_lint.check_memo_knob({
        ast_lint.CONFIG_PATH: _MEMO_KNOB_OK,
        "chandy_lamport_tpu/utils/memocache.py": bad_resolver})
    details = [v.detail for v in vs]
    assert any("does not consult ENGINE_KNOBS" in d for d in details), details
    assert any("restates the memo spellings inline" in d
               for d in details), details


def test_memo_schema_single_named_constant():
    # restated literal in a schema-stamping dict
    vs = ast_lint.check_memo_schema({ast_lint.MEMOCACHE_PATH: (
        "MEMOCACHE_SCHEMA_VERSION = 1\n"
        "def put():\n"
        "    return {\"schema\": 1, \"digest\": \"d\"}\n"
    )})
    assert any("restated literal 1" in v.detail for v in vs), \
        [v.detail for v in vs]
    # re-assignment outside memocache.py
    vs = ast_lint.check_memo_schema({
        ast_lint.MEMOCACHE_PATH: "MEMOCACHE_SCHEMA_VERSION = 1\n",
        "chandy_lamport_tpu/parallel/batch.py":
            "MEMOCACHE_SCHEMA_VERSION = 2\n"})
    assert any("lives only in utils/memocache.py" in v.detail
               for v in vs), [v.detail for v in vs]
    # the clean shape (Name reference at the stamp site) passes
    assert ast_lint.check_memo_schema({ast_lint.MEMOCACHE_PATH: (
        "MEMOCACHE_SCHEMA_VERSION = 1\n"
        "def put():\n"
        "    return {\"schema\": MEMOCACHE_SCHEMA_VERSION}\n"
    )}) == []


_SERVE_KNOB_OK = (
    "ENGINE_KNOBS = {\n"
    "    \"memo\": (\"off\", \"admit\", \"full\"),\n"
    "    \"serve_policy\": (\"edf\", \"fifo\"),\n"
    "}\n"
)
_RESOLVE_SERVE_OK = (
    "from chandy_lamport_tpu.config import ENGINE_KNOBS\n"
    "def resolve_serve_policy(policy):\n"
    "    if policy not in ENGINE_KNOBS[\"serve_policy\"]:\n"
    "        raise ValueError(policy)\n"
    "    return policy\n"
)


def test_serve_knob_requires_table_row_and_default_order():
    # missing row
    vs = ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH: "ENGINE_KNOBS = {\"memo\": (\"off\",)}\n",
        "chandy_lamport_tpu/serving/admission.py": _RESOLVE_SERVE_OK})
    assert any("no 'serve_policy' row" in v.detail for v in vs), \
        [v.detail for v in vs]
    # row present but reordered: edf (the default) must lead
    vs = ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH:
            "ENGINE_KNOBS = {\"serve_policy\": (\"fifo\", \"edf\")}\n",
        "chandy_lamport_tpu/serving/admission.py": _RESOLVE_SERVE_OK})
    assert any("'edf' leads" in v.detail for v in vs), [v.detail for v in vs]
    # the clean shape passes
    assert ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH: _SERVE_KNOB_OK,
        "chandy_lamport_tpu/serving/admission.py": _RESOLVE_SERVE_OK}) == []


def test_serve_knob_rejects_inline_spelling_copy():
    bad_resolver = (
        "def resolve_serve_policy(policy):\n"
        "    if policy not in (\"edf\", \"fifo\"):\n"
        "        raise ValueError(policy)\n"
        "    return policy\n"
    )
    vs = ast_lint.check_serve_knob({
        ast_lint.CONFIG_PATH: _SERVE_KNOB_OK,
        "chandy_lamport_tpu/serving/admission.py": bad_resolver})
    details = [v.detail for v in vs]
    assert any("does not consult ENGINE_KNOBS" in d for d in details), details
    assert any("restates the policy spellings inline" in d
               for d in details), details


def test_serve_schema_single_named_constant():
    # restated literal at a serve_schema stamp site
    vs = ast_lint.check_serve_schema({ast_lint.SERVING_SERVER_PATH: (
        "SERVE_SCHEMA_VERSION = 1\n"
        "def row():\n"
        "    return {\"serve_schema\": 1, \"kind\": \"serve_interval\"}\n"
    )})
    assert any("restated literal 1" in v.detail for v in vs), \
        [v.detail for v in vs]
    # re-assignment outside serving/server.py
    vs = ast_lint.check_serve_schema({
        ast_lint.SERVING_SERVER_PATH: "SERVE_SCHEMA_VERSION = 1\n",
        "chandy_lamport_tpu/cli.py": "SERVE_SCHEMA_VERSION = 2\n"})
    assert any("lives only in serving/server.py" in v.detail
               for v in vs), [v.detail for v in vs]
    # the clean shape (Name reference at the stamp site) passes
    assert ast_lint.check_serve_schema({ast_lint.SERVING_SERVER_PATH: (
        "SERVE_SCHEMA_VERSION = 1\n"
        "def row():\n"
        "    return {\"serve_schema\": SERVE_SCHEMA_VERSION}\n"
    )}) == []


def test_registry_loader_reads_legacy_and_schema2(tmp_path):
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"k": "abc"}))
    entries, ver = load_registry(str(legacy))
    assert entries == {"k": "abc"} and ver is None

    v2 = tmp_path / "v2.json"
    v2.write_text(json.dumps(
        {"schema": 2, "jax": "9.9.9", "entries": {"k": "def"}}))
    entries, ver = load_registry(str(v2))
    assert entries == {"k": "def"} and ver == "9.9.9"

    missing, ver = load_registry(str(tmp_path / "nope.json"))
    assert missing == {} and ver is None


def test_shipped_registry_is_schema2_and_version_stamped():
    entries, ver = load_registry()
    assert entries, "fingerprints.json has no entries"
    assert ver, "fingerprints.json does not record the jax version"


@pytest.mark.slow
def test_jaxpr_fast_plane_clean():
    from tools.staticcheck import jaxpr_audit
    vs, audited, _ = jaxpr_audit.audit("fast", check_fingerprints=True)
    kept, _allowed = apply_allowlist(vs)
    assert kept == [], [v.to_dict() for v in kept]
    assert len(audited) == 5
