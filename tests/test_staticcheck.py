"""tools/staticcheck: the AST lint plane and the fingerprint registry.

The AST tests are jax-free and near-instant; the jaxpr plane traces real
entries and is marked slow (the tier-1 gate runs -m 'not slow').
"""

import json
import os

import pytest

from tools.staticcheck import apply_allowlist
from tools.staticcheck import ast_lint
from tools.staticcheck.jaxpr_audit import load_registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ast_plane_clean_on_tree():
    # the shipped tree must satisfy its own structural invariants
    # (err-bit registry, knob pattern, ckpt history, scatter modes)
    kept, _allowed = apply_allowlist(ast_lint.lint_tree(REPO_ROOT))
    assert kept == [], [v.to_dict() for v in kept]


def test_err_bit_registry_rejects_non_power_of_two():
    sources = {ast_lint.STATE_PATH: (
        "ERR_A = 1\n"
        "ERR_B = 3\n"
        "ERROR_REGISTRY = ((\"ERR_A\", ERR_A, \"a\"), (\"ERR_B\", ERR_B, \"b\"))\n"
        "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
        "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
        "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
    )}
    vs = ast_lint.check_error_bits(sources)
    assert any("not a power of two" in v.detail for v in vs), \
        [v.detail for v in vs]


def test_err_bit_registry_accepts_tuple_and_call_rows():
    # rows as bare tuples and as constructor calls must both parse
    for row_b in ("(\"ERR_B\", ERR_B, \"b\")", "ErrorBit(\"ERR_B\", ERR_B, \"b\")"):
        sources = {ast_lint.STATE_PATH: (
            "ERR_A = 1\n"
            "ERR_B = 2\n"
            "ERROR_REGISTRY = ((\"ERR_A\", ERR_A, \"a\"), " + row_b + ")\n"
            "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
            "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
            "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
        )}
        assert ast_lint.check_error_bits(sources) == []


def test_err_bit_registry_catches_name_bit_mismatch():
    sources = {ast_lint.STATE_PATH: (
        "ERR_A = 1\n"
        "ERR_B = 2\n"
        "ERROR_REGISTRY = ((\"ERR_A\", ERR_B, \"a\"), (\"ERR_B\", ERR_B, \"b\"))\n"
        "NUM_ERROR_BITS = len(ERROR_REGISTRY)\n"
        "ERROR_NAMES = {r[1]: r[2] for r in ERROR_REGISTRY}\n"
        "ERROR_BIT_NAMES = {r[1]: r[0] for r in ERROR_REGISTRY}\n"
    )}
    vs = ast_lint.check_error_bits(sources)
    assert any("name and bit disagree" in v.detail for v in vs), \
        [v.detail for v in vs]


def test_registry_loader_reads_legacy_and_schema2(tmp_path):
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"k": "abc"}))
    entries, ver = load_registry(str(legacy))
    assert entries == {"k": "abc"} and ver is None

    v2 = tmp_path / "v2.json"
    v2.write_text(json.dumps(
        {"schema": 2, "jax": "9.9.9", "entries": {"k": "def"}}))
    entries, ver = load_registry(str(v2))
    assert entries == {"k": "def"} and ver == "9.9.9"

    missing, ver = load_registry(str(tmp_path / "nope.json"))
    assert missing == {} and ver is None


def test_shipped_registry_is_schema2_and_version_stamped():
    entries, ver = load_registry()
    assert entries, "fingerprints.json has no entries"
    assert ver, "fingerprints.json does not record the jax version"


@pytest.mark.slow
def test_jaxpr_fast_plane_clean():
    from tools.staticcheck import jaxpr_audit
    vs, audited, _ = jaxpr_audit.audit("fast", check_fingerprints=True)
    kept, _allowed = apply_allowlist(vs)
    assert kept == [], [v.to_dict() for v in kept]
    assert len(audited) == 5
