"""Streaming engine (continuous lane scheduling): stream-vs-static parity.

The oracle for ``BatchedRunner.run_stream`` is the static path itself: a job
that streams through whatever slot the admitter hands it must produce the
SAME per-job summary — time, error bits, final token vector, snapshot
lifecycle — as that job run alone on the static ``run()`` path, bit for
bit. The per-lane tick sequence is slot-independent because every piece of
per-job context (script cursor, fault stream key, delay-sampler state)
lives in the lane's DenseState leaves and is reset + reseeded from the
JobPool row at admission (ops/tick.reset_lanes, parallel/batch docstring).

Tier-1 keeps the shapes tiny (ring-8, a handful of jobs) and shares one
module-scoped runner so the jitted stream step compiles once; the deep
heterogeneous sweep (J=48 through B=16, both schedulers, fault-armed
subset) is ``slow``.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.models.faults import JaxFaults
from chandy_lamport_tpu.models.workloads import ring_topology, stream_jobs
from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
from chandy_lamport_tpu.parallel.batch import BatchedRunner, compile_events
from chandy_lamport_tpu.utils import checkpoint as ckpt_mod
from chandy_lamport_tpu.utils.checkpoint import (
    CheckpointError,
    load_state,
    save_state,
)

TOPO = ring_topology(8)
CFG = SimConfig.for_workload(snapshots=4, max_recorded=128)
J, B = 8, 4


def _delay():
    return make_fast_delay("hash", 11)


def _static_rows(sched, jobs, fault_key=None, faults=None, quarantine=False):
    """Oracle: each job alone on the static run() path (a batch-J runner
    sliced to one lane, so init/leaves match the streaming admitter's
    fresh-template reset exactly)."""
    r = BatchedRunner(TOPO, CFG, _delay(), len(jobs), scheduler=sched,
                      faults=faults, quarantine=quarantine)
    st = r.init_batch()
    if fault_key is not None:
        st = st._replace(fault_key=np.asarray(fault_key))
    rows = []
    for j, ev in enumerate(jobs):
        sj = jax.tree_util.tree_map(lambda x: x[j:j + 1], st)
        out = r.run(sj, compile_events(r.topo, ev))
        rows.append({
            "job": j,
            "time": int(out.time[0]),
            "error": int(out.error[0]),
            "tokens": np.asarray(out.tokens[0]).astype(int).tolist(),
            "snapshots_started": int(np.sum(np.asarray(out.started[0]))),
        })
    return rows


def _assert_rows_match(stream_rows, static_rows):
    assert len(stream_rows) == len(static_rows)
    for a, b in zip(stream_rows, static_rows):
        for k in ("job", "time", "error", "tokens", "snapshots_started"):
            assert a[k] == b[k], (a["job"], k, a[k], b[k])


@pytest.fixture(scope="module")
def sync_runner(ring8_sync_stream_runner):
    # the session-scoped shared instance (conftest): same (TOPO, CFG,
    # delay, B) shape as declared above, compiled once for the whole gate
    return ring8_sync_stream_runner


@pytest.fixture(scope="module")
def jobs():
    return stream_jobs(TOPO, J, seed=5, base_phases=3, max_phases=12)


@pytest.fixture(scope="module")
def pool(sync_runner, jobs):
    return sync_runner.pack_jobs(jobs)


@pytest.fixture(scope="module")
def sync_stream(sync_runner, pool):
    state, stream = sync_runner.run_stream(pool, stretch=3, drain_chunk=16)
    return (sync_runner.stream_results(stream),
            sync_runner.summarize_stream(stream))


def test_stream_drains_queue_and_recycles_slots(sync_stream):
    rows, summ = sync_stream
    assert summ["jobs_done"] == J
    assert summ["jobs_admitted"] == J
    assert len(rows) == J
    # every admission beyond each slot's first is a refill
    assert summ["refills"] == J - B
    assert 0.0 < summ["occupancy"] <= 1.0
    assert summ["results_evicted"] == 0


@pytest.mark.slow  # exact-leg parity below keeps the claim in tier-1
def test_stream_vs_static_parity_sync(sync_stream, jobs):
    _assert_rows_match(sync_stream[0], _static_rows("sync", jobs))


def test_gang_admission_same_results(sync_runner, pool, sync_stream):
    # gang = static batching on the same executable: identical per-job
    # rows (admit steps differ — that's the whole point), lower occupancy
    _, stream = sync_runner.run_stream(pool, stretch=3, drain_chunk=16,
                                       admission="gang")
    rows = sync_runner.stream_results(stream)
    for a, b in zip(sync_stream[0], rows):
        assert a == {**b, "admit_step": a["admit_step"]}
    summ = sync_runner.summarize_stream(stream)
    assert summ["jobs_done"] == J
    assert summ["occupancy"] <= sync_stream[1]["occupancy"]


def test_checkpoint_v6_kill_and_resume_mid_queue(sync_runner, pool,
                                                 tmp_path):
    # same stretch/drain_chunk as the parity fixture -> the jitted step is
    # already compiled; the save/kill/load/finish trip must land on the
    # byte-identical final (state, stream) carry, results ring included
    ref_state, ref_stream = sync_runner.run_stream(pool, stretch=3,
                                                   drain_chunk=16)
    path = str(tmp_path / "stream.npz")
    _, killed = sync_runner.run_stream(pool, stretch=3, drain_chunk=16,
                                       checkpoint=path, checkpoint_every=2,
                                       kill_after_saves=2)
    assert int(killed.jobs_done) < J, "kill landed after the queue drained"
    like = (sync_runner.init_batch(), sync_runner.init_stream(pool))
    (state, stream), meta = load_state(path, like)
    assert meta["jobs_done"] == int(stream.jobs_done)
    state, stream = sync_runner.run_stream(pool, stretch=3, drain_chunk=16,
                                           state=state, stream=stream)
    assert (sync_runner.stream_results(stream)
            == sync_runner.stream_results(ref_stream))
    for a, b in zip(jax.tree_util.tree_leaves((ref_state, ref_stream)),
                    jax.tree_util.tree_leaves((state, stream))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_stale_version_error_names_current_range(tmp_path, monkeypatch):
    # the supported range in the error must have widened to v10 (the
    # prefix-fork format): an operator holding a too-NEW file learns
    # both sides of the mismatch
    path = str(tmp_path / "v99.npz")
    tree = {"x": np.zeros(3, np.int32)}
    monkeypatch.setattr(ckpt_mod, "_FORMAT_VERSION", 99)
    save_state(path, tree)
    monkeypatch.undo()
    with pytest.raises(CheckpointError,
                       match=r"version 99.*supported version range "
                             r"v\d+\.\.v10"):
        load_state(path, tree)


@pytest.mark.slow
def test_stream_vs_static_parity_exact():
    runner = BatchedRunner(TOPO, CFG, _delay(), B, scheduler="exact")
    jobs = stream_jobs(TOPO, J, seed=5, base_phases=3, max_phases=12)
    _, stream = runner.run_stream(runner.pack_jobs(jobs), stretch=3,
                                  drain_chunk=16)
    _assert_rows_match(runner.stream_results(stream),
                       _static_rows("exact", jobs))


@pytest.mark.slow
@pytest.mark.parametrize("sched", ["exact", "sync"])
def test_stream_deep_heterogeneous_parity(sched):
    # the acceptance sweep: J=48 heavy-tailed jobs through B=16 slots with
    # a fault adversary armed on every third job + quarantine — per-job
    # summaries bit-identical to each job alone on the static path, with
    # the SAME per-job fault stream (pool fault_key replayed wherever the
    # job lands)
    jcount, slots = 48, 16
    faults = JaxFaults(7, drop_rate=0.05, dup_rate=0.05,
                       max_delay=_delay().max_delay)
    runner = BatchedRunner(TOPO, CFG, _delay(), slots, scheduler=sched,
                           faults=faults, quarantine=True)
    jobs = stream_jobs(TOPO, jcount, seed=6, base_phases=3, max_phases=16)
    armed = np.arange(jcount) % 3 == 0
    pool = runner.pack_jobs(jobs, fault_armed=armed)
    _, stream = runner.run_stream(pool, stretch=4, drain_chunk=16)
    rows = runner.stream_results(stream)
    summ = runner.summarize_stream(stream)
    assert summ["jobs_done"] == jcount
    assert summ["refills"] == jcount - slots
    _assert_rows_match(rows, _static_rows(sched, jobs,
                                          fault_key=pool.fault_key,
                                          faults=faults, quarantine=True))
    # disarmed jobs never see the adversary, whichever slot they streamed
    # through
    for r in rows:
        if not armed[r["job"]]:
            assert r["error"] == 0
