"""Differential validation of the dense sync scheduler (ops/tick._sync_tick)
against the independent sequential oracle (core/syncsim.SyncOracle) on random
graphs and storm programs under a shared fixed delay."""

import random

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import recorded_window, DenseTopology, decode_snapshot
from chandy_lamport_tpu.core.syncsim import SyncOracle
from chandy_lamport_tpu.models.delay import FixedDelay
from chandy_lamport_tpu.models.workloads import (
    StormProgram,
    erdos_renyi,
    scale_free,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner


def _random_program(rng, topo, phases, max_snapshots):
    amounts = np.zeros((phases, topo.e), np.int32)
    floor = topo.tokens0.astype(np.int64).copy()
    for ph in range(phases):
        for e in rng.sample(range(topo.e), k=max(1, topo.e // 3)):
            src = int(topo.edge_src[e])
            if floor[src] >= 2:
                amt = rng.randrange(1, 3)
                amounts[ph, e] += amt
                floor[src] -= amt
    n_snaps = rng.randrange(1, max_snapshots)
    snap = np.full((phases, 2), -1, np.int32)
    sched = []
    used = 0
    for _ in range(n_snaps):
        ph = rng.randrange(phases)
        node = rng.randrange(topo.n)
        sched.append((ph, node))
    per_phase = {}
    for ph, node in sched:
        per_phase.setdefault(ph, []).append(node)
    for ph, nodes in per_phase.items():
        nodes = sorted(set(nodes))[:2]
        snap[ph, :len(nodes)] = nodes
        used += len(nodes)
    return StormProgram(amounts, snap), used


def test_dense_sync_matches_oracle_large_graph():
    """One large-graph case (N=128): the randomized suites stay small for
    CI speed, but the prefix-count delivery selection, segment reductions
    and window bookkeeping should also be pinned at a size where per-edge
    structures genuinely interleave (uint16 window planes on)."""
    rng = random.Random(31337)
    spec = erdos_renyi(128, 3.0, seed=41, tokens=100)
    topo = DenseTopology(spec)
    phases, delay = 12, 3
    prog, n_snaps = _random_program(rng, topo, phases, max_snapshots=6)

    runner = BatchedRunner(spec, SimConfig(queue_capacity=32, max_recorded=256,
                                           max_snapshots=8,
                                           window_dtype="uint16"),
                           FixedJaxDelay(delay), batch=1, scheduler="sync",
                           check_every=3)
    final = jax.device_get(runner.run_storm(runner.init_batch(), prog))
    lane = jax.tree_util.tree_map(lambda x: x[0], final)
    assert int(lane.error) == 0

    oracle = SyncOracle(topo, FixedDelay(delay))
    amounts, snap = np.asarray(prog.amounts), np.asarray(prog.snap)
    for ph in range(phases):
        oracle.bulk_send([int(a) for a in amounts[ph]])
        nodes = [int(x) for x in snap[ph] if x >= 0]
        if nodes:
            oracle.start_snapshots(nodes)
        oracle.tick()
    oracle.drain_and_flush()

    assert oracle.time == int(lane.time)
    assert oracle.tokens == [int(t) for t in lane.tokens]
    for sid in range(n_snaps):
        for e in range(topo.e):
            assert (oracle.recorded[sid].get(e, [])
                    == recorded_window(lane, sid, e)), (sid, e)


@pytest.mark.parametrize("case", range(6))
def test_dense_sync_matches_oracle(case):
    rng = random.Random(5000 + case)
    n = rng.randrange(4, 14)
    spec = (erdos_renyi(n, 2.5, seed=case, tokens=60) if case % 2
            else scale_free(n, 2, seed=case, tokens=60))
    delay = rng.randrange(1, 5)
    topo = DenseTopology(spec)
    phases = rng.randrange(6, 16)
    prog, n_snaps = _random_program(rng, topo, phases, max_snapshots=6)

    # dense kernel, one lane
    runner = BatchedRunner(spec, SimConfig(queue_capacity=32, max_recorded=64),
                           FixedJaxDelay(delay), batch=1, scheduler="sync")
    final = jax.device_get(runner.run_storm(runner.init_batch(), prog))
    lane = jax.tree_util.tree_map(lambda x: x[0], final)
    assert int(lane.error) == 0

    # oracle
    oracle = SyncOracle(topo, FixedDelay(delay))
    amounts = np.asarray(prog.amounts)
    snap = np.asarray(prog.snap)
    for ph in range(phases):
        oracle.bulk_send([int(a) for a in amounts[ph]])
        nodes = [int(x) for x in snap[ph] if x >= 0]
        if nodes:
            oracle.start_snapshots(nodes)
        oracle.tick()
    oracle.drain_and_flush()

    assert oracle.next_sid == int(lane.next_sid) == n_snaps
    assert oracle.time == int(lane.time)
    assert oracle.tokens == [int(t) for t in lane.tokens]
    assert all(len(q) == 0 for q in oracle.queues)
    assert int(lane.q_len.sum()) == 0
    for sid in range(n_snaps):
        assert oracle.completed[sid] == int(lane.completed[sid]) == topo.n
        # frozen balances per node
        for node in range(topo.n):
            assert oracle.frozen[sid][node] == int(lane.frozen[sid, node]), (
                f"sid {sid} node {node}")
        # recorded channel contents, per edge in arrival order
        for e in range(topo.e):
            want = oracle.recorded[sid].get(e, [])
            got = recorded_window(lane, sid, e)
            assert want == got, f"sid {sid} edge {e}"
