"""Device flight recorder (utils/tracing.py): decode parity with the
reference Logger, overflow accounting, observer-effect zero, checkpoint-v7
ring round-trip, and the telemetry JSONL contract.

The headline guarantees (ISSUE 7):

  * a recorded dense-backend run decodes to EXACTLY the parity backend's
    EpochTrace.pretty() output on the reference goldens — the device ring
    captures the same events at the same sites the reference Logger logs;
  * arming the trace never perturbs the simulation (every non-trace state
    leaf bit-identical to the trace=None run — the faults=None pattern);
  * ring wrap is never silent: the dropped counter accounts for every
    overwritten event and the ring keeps the chronological TAIL;
  * the ring rides checkpoints bit-exactly (format v7), so a killed run's
    resume carries its flight history forward.
"""

import numpy as np
import pytest

from chandy_lamport_tpu.api import run_events_file
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path
from chandy_lamport_tpu.utils.tracing import (
    JaxTrace,
    TELEMETRY_SCHEMA_VERSION,
    TelemetryWriter,
    read_telemetry,
    trace_counts,
)

GOLDEN_IDS = [t[1].removesuffix(".events") for t in REFERENCE_TESTS]
SMALL_TOP, SMALL_EVENTS = "2nodes.top", "2nodes-message.events"


def _run_small(trace=True, config=None):
    return run_events_file(fixture_path(SMALL_TOP),
                           fixture_path(SMALL_EVENTS),
                           backend="jax", trace=trace, config=config)


@pytest.fixture(scope="module")
def small_traced():
    """One traced dense run of the smallest golden, shared by the fast
    tests (each distinct trace_capacity is a fresh compile)."""
    return _run_small()


def test_trace_pretty_matches_parity_on_golden(small_traced):
    _, dsim = small_traced
    _, psim = run_events_file(fixture_path(SMALL_TOP),
                              fixture_path(SMALL_EVENTS),
                              backend="parity", trace=True)
    assert dsim.trace.pretty() == psim.trace.pretty()
    rec, dropped = dsim.trace.counts()
    assert rec == len(dsim.trace.events) and dropped == 0


@pytest.mark.slow
@pytest.mark.parametrize("top,events,snaps", REFERENCE_TESTS,
                         ids=GOLDEN_IDS)
def test_trace_pretty_matches_parity_all_goldens(top, events, snaps):
    _, psim = run_events_file(fixture_path(top), fixture_path(events),
                              backend="parity", trace=True)
    _, dsim = run_events_file(fixture_path(top), fixture_path(events),
                              backend="jax", trace=True)
    assert dsim.trace.pretty() == psim.trace.pretty()


@pytest.mark.slow
@pytest.mark.parametrize("top,events,snaps", REFERENCE_TESTS,
                         ids=GOLDEN_IDS)
def test_trace_off_bit_identity_goldens(top, events, snaps):
    """Arming the recorder must not move a single bit of simulation state:
    the traced run's final DenseState equals the trace=None run's on every
    non-trace leaf (and the snapshots it decodes are identical)."""
    off_snaps, off = run_events_file(fixture_path(top), fixture_path(events),
                                     backend="jax", trace=False)
    on_snaps, on = run_events_file(fixture_path(top), fixture_path(events),
                                   backend="jax", trace=True)
    assert off_snaps == on_snaps
    import jax

    ha = {k: v for k, v in off._host()._asdict().items()
          if not k.startswith("tr_")}
    hb = {k: v for k, v in on._host()._asdict().items()
          if not k.startswith("tr_")}
    fa, ta = jax.tree_util.tree_flatten(ha)
    fb, tb = jax.tree_util.tree_flatten(hb)
    assert ta == tb
    for xa, xb in zip(fa, fb):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))


def test_trace_wraparound_and_dropped_count(small_traced):
    """A capacity-4 ring on a 9-event run wraps: the dropped counter owns
    the difference and the ring holds the chronological tail."""
    _, full = small_traced
    all_events = full.trace.events
    assert len(all_events) > 4
    _, capped = _run_small(config=SimConfig(trace_capacity=4))
    rec, dropped = capped.trace.counts()
    assert rec == 4
    assert dropped == len(all_events) - 4
    assert capped.trace.events == all_events[-4:]


@pytest.mark.slow  # ~27 s; cheaper roundtrips in test_recovery stay tier-1
def test_checkpoint_v7_ring_roundtrip(tmp_path):
    """Kill -> resume through a checkpoint carries the ring bit-exactly:
    a storm split in two with a save/load between the chunks finishes with
    every leaf — tr_* included — identical to the uninterrupted run."""
    import jax

    from chandy_lamport_tpu.models.workloads import (
        StormProgram,
        ring_topology,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.checkpoint import load_state, save_state

    spec = ring_topology(4, tokens=20)
    cfg = SimConfig.for_workload(snapshots=2)
    runner = BatchedRunner(spec, cfg, FixedJaxDelay(1), batch=2,
                           trace=JaxTrace(capacity=128))
    prog = storm_program(
        runner.topo, phases=8, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, 2, 1, 2,
                                            max_phases=8))
    full = jax.device_get(runner.run_storm(runner.init_batch(), prog))
    amounts, snap = np.asarray(prog.amounts), np.asarray(prog.snap)
    mid = runner.run_storm(runner.init_batch(),
                           StormProgram(amounts[:4], snap[:4]), drain=False)
    path = str(tmp_path / "trace_ck.npz")
    save_state(path, mid, meta={"next_phase": 4})
    loaded, meta = load_state(path, runner.init_batch())
    assert meta["next_phase"] == 4
    # the ring survived the save/load byte-for-byte
    for name in ("tr_meta", "tr_data", "tr_tick", "tr_count", "tr_on"):
        assert np.array_equal(np.asarray(getattr(loaded, name)),
                              np.asarray(jax.device_get(
                                  getattr(mid, name)))), name
    resumed = jax.device_get(
        runner.run_storm(loaded, StormProgram(amounts[4:], snap[4:])))
    for name, leaf in full._asdict().items():
        assert np.array_equal(np.asarray(leaf),
                              np.asarray(getattr(resumed, name))), name
    rec, dropped = trace_counts(resumed)
    assert rec > 0 and dropped == 0


def test_telemetry_writer_roundtrip(tmp_path):
    """JSONL contract: schema-stamped records round-trip, torn trailing
    lines are skipped, and a newer schema version fails loudly."""
    path = str(tmp_path / "t.jsonl")
    with TelemetryWriter(path) as tw:
        tw.write("run", {"value": 1.5, "name": "a"})
        tw.write("event", {"tick": 3})
    with open(path, "a") as f:
        f.write('{"torn": ')  # a crash mid-write must not poison the file
    records = read_telemetry(path)
    assert [r["kind"] for r in records] == ["run", "event"]
    assert all(r["schema"] == TELEMETRY_SCHEMA_VERSION for r in records)
    assert records[0]["value"] == 1.5 and records[1]["tick"] == 3
    with open(path, "w") as f:
        f.write('{"schema": %d, "kind": "run"}\n'
                % (TELEMETRY_SCHEMA_VERSION + 1))
    with pytest.raises(ValueError):
        read_telemetry(path)
