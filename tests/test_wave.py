"""Differential coverage for the wave-exact tick (ops/tick._wave_tick).

The wave formulation reassociates the reference fold (sim.go:71-95)
across destinations: every same-tick marker bound for a distinct
destination is processed in one vectorized step, with delay draws served
from tick-start fold-order stream positions. It must be BIT-IDENTICAL to
the cascade formulation — same state planes, same error bits, same
sampler stream position — for position-addressable samplers
(JaxDelay.position_streams: FixedJaxDelay, HashJaxDelay), and must
refuse order-dependent samplers at construction.
"""

import random

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import (
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.models.delay import FixedDelay
from chandy_lamport_tpu.models.workloads import (
    erdos_renyi,
    ring_topology,
    scale_free,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import (
    FixedJaxDelay,
    GoExactJaxDelay,
    HashJaxDelay,
    UniformJaxDelay,
)
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.fixtures import TopologySpec


def _storm_final_states(spec, cfg, delay, batch, phases, snapshots,
                        impls=("cascade", "wave")):
    outs = []
    for impl in impls:
        r = BatchedRunner(spec, cfg, delay, batch=batch, scheduler="exact",
                          exact_impl=impl)
        prog = storm_program(
            r.topo, phases=phases, amount=2,
            snapshot_phases=staggered_snapshots(r.topo, snapshots))
        outs.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    return outs


def _assert_states_identical(a, b):
    """Every DenseState field bit-equal — including the ring planes, the
    shared log, the recording windows, the sticky error mask, and the
    delay sampler's stream position (the wave's whole claim)."""
    from chandy_lamport_tpu.utils.compare import dense_state_mismatches

    assert dense_state_mismatches(a, b) == []


@pytest.mark.parametrize("case_seed", [
    # the whole battery runs in full passes; the fixed-delay and
    # capacity-edge wave-vs-cascade differentials below stay tier-1
    # (the PR-3 re-tiering mechanism — tier-1 lives under a hard
    # wall-clock budget and each seed costs a ~11-16 s compile+storm;
    # seed 0 moved out when the serving-fleet tests joined the gate)
    pytest.param(0, marks=pytest.mark.slow),
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(3, marks=pytest.mark.slow)])
def test_wave_vs_cascade_random_storms(case_seed):
    """Randomized graph families under the hash sampler (per-lane
    position-addressable streams — the production exact-bench sampler)."""
    rng = random.Random(5100 + case_seed)
    spec = [
        lambda: ring_topology(8, tokens=40),
        lambda: erdos_renyi(24, 2.5, seed=case_seed, tokens=60),
        lambda: scale_free(32, 2, seed=case_seed, tokens=60),
        lambda: erdos_renyi(12, 4.0, seed=40 + case_seed, tokens=60),
    ][case_seed]()
    cfg = SimConfig(max_snapshots=4, queue_capacity=24, max_recorded=48)
    a, b = _storm_final_states(spec, cfg, HashJaxDelay(seed=rng.randrange(
        1 << 20)), batch=8, phases=6, snapshots=3)
    assert int(np.max(a.error)) == 0  # clean runs, then bit-compare all
    _assert_states_identical(a, b)


@pytest.mark.slow  # the capacity-edge leg keeps wave-vs-cascade tier-1
def test_wave_vs_cascade_marker_pileup():
    """The shape the wave exists for: a complete digraph where every node
    snapshots in the same phase, so single ticks deliver many markers to
    the SAME destination (per-destination conflict depth > 1) while many
    destinations are hit at once. All interleavings — same-destination
    sequencing, token prefixes, draw positions — must match the cascade."""
    n = 8
    spec = TopologySpec(
        [(f"N{i}", 200) for i in range(n)],
        sorted((f"N{i}", f"N{j}") for i in range(n) for j in range(n)
               if i != j))
    cfg = SimConfig(max_snapshots=8, queue_capacity=32, max_recorded=96)
    outs = []
    for impl in ("cascade", "wave"):
        r = BatchedRunner(spec, cfg, HashJaxDelay(seed=99), batch=4,
                          scheduler="exact", exact_impl=impl)
        # every node initiates in phase 0: markers for 8 snapshots flood
        # every destination within a few ticks of each other
        prog = storm_program(r.topo, phases=5, amount=2,
                             snapshot_phases=[(0, k) for k in range(n)])
        outs.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
    a, b = outs
    assert int(np.max(a.error)) == 0
    assert bool(np.all(a.started))  # all 8 slots started in every lane
    _assert_states_identical(a, b)


@pytest.mark.slow  # ~12 s; test_wave_capacity_edge_matches_cascade keeps the
# wave-vs-cascade bit-identity differential in tier-1
def test_wave_matches_cascade_and_parity_fixed_delay():
    """Scalar event path (DenseSim injections + drain) under FixedDelay,
    checked against the parity oracle too: decoded snapshots and final
    balances, plus full-state equality between the two jax impls."""
    from chandy_lamport_tpu.api import run_events

    ids = [f"N{i}" for i in range(5)]
    topo = TopologySpec([(i, 50) for i in ids],
                        sorted((a, b) for a in ids for b in ids if a != b))
    events = [SnapshotEvent("N0"), SnapshotEvent("N2")]
    for burst in range(3):
        for src in ids:
            for dst in ids:
                if src != dst:
                    events.append(PassTokenEvent(src, dst, burst + 1))
        events.append(TickEvent(1))
        events.append(SnapshotEvent(ids[burst]))

    p_snaps, p_sim = run_events("parity", topo, events, FixedDelay(3))
    cfg = SimConfig(max_snapshots=8, queue_capacity=64, max_recorded=128)
    results = []
    for impl in ("cascade", "wave"):
        snaps, sim = run_events("jax", topo, events, FixedDelay(3), cfg,
                                exact_impl=impl)
        results.append((snaps, sim))
    assert results[0][0] == results[1][0] == p_snaps
    assert (results[0][1].node_tokens() == results[1][1].node_tokens()
            == p_sim.node_tokens())
    _assert_states_identical(results[0][1]._host(), results[1][1]._host())


def test_wave_capacity_edge_matches_cascade():
    """The wave pops selected heads up front exactly like the cascade, so
    it inherits the cascade's side of the documented fold divergence at
    exactly-full C (tests/test_differential.test_cascade_fold_capacity_edge):
    clean at C where the fold overflows, bit-identical to the cascade."""
    from chandy_lamport_tpu.api import run_events

    C = 4
    topo = TopologySpec([("N1", 10), ("N2", 10)],
                        [("N1", "N2"), ("N2", "N1")])
    events = [PassTokenEvent("N2", "N1", 1)] * C
    events += [SnapshotEvent("N1"), TickEvent(1)]
    outs = []
    for impl in ("cascade", "wave"):
        snaps, sim = run_events("jax", topo, events, FixedDelay(1),
                                SimConfig(queue_capacity=C, max_recorded=16),
                                exact_impl=impl)
        outs.append((snaps, sim))
    assert outs[0][0] == outs[1][0]
    _assert_states_identical(outs[0][1]._host(), outs[1][1]._host())


@pytest.mark.slow
def test_wave_push_overflow_matches_cascade():
    """The wave's vectorized re-broadcast must flag ERR_QUEUE_OVERFLOW at
    exactly the same boundary as the cascade's sequential _push: a marker
    cascade pushing onto a ring that is STILL full at push time (no pop
    made room — the queued tokens are not yet delivery-eligible).

    Construction (FixedDelay(2), C=4): snapshot at N1 at t=0 (marker
    receive time 2); C tokens N2->N1 sent at t=1 (receive time 3). At
    t=2 the marker is the only eligible head: N2 creates its local
    snapshot and re-broadcasts onto the full N2->N1 ring — overflow, in
    both formulations identically."""
    from chandy_lamport_tpu.api import run_events
    from chandy_lamport_tpu.core.dense import DenseBackendError

    C = 4
    topo = TopologySpec([("N1", 10), ("N2", 10)],
                        [("N1", "N2"), ("N2", "N1")])
    events = [SnapshotEvent("N1"), TickEvent(1)]
    events += [PassTokenEvent("N2", "N1", 1)] * C
    events += [TickEvent(2)]
    for impl in ("cascade", "wave"):
        with pytest.raises(DenseBackendError, match="queue capacity"):
            run_events("jax", topo, events, FixedDelay(2),
                       SimConfig(queue_capacity=C, max_recorded=16),
                       exact_impl=impl)
    # one more slot: both run clean and bit-identical
    outs = []
    for impl in ("cascade", "wave"):
        snaps, sim = run_events("jax", topo, events, FixedDelay(2),
                                SimConfig(queue_capacity=C + 1,
                                          max_recorded=16),
                                exact_impl=impl)
        outs.append((snaps, sim))
    assert outs[0][0] == outs[1][0]
    _assert_states_identical(outs[0][1]._host(), outs[1][1]._host())


def test_wave_refuses_order_dependent_samplers():
    """GoExact (the vendored sequential Go stream) and Uniform (a split
    chain) cannot serve draws by position; wave must fail loudly at
    construction, not silently change the stream."""
    spec = ring_topology(4, tokens=10)
    cfg = SimConfig(max_snapshots=2)
    for delay in (UniformJaxDelay(seed=1),):
        with pytest.raises(ValueError, match="position-addressable"):
            BatchedRunner(spec, cfg, delay, batch=2, scheduler="exact",
                          exact_impl="wave")
    # GoExact needs x64; construct the kernel directly to avoid state init
    from chandy_lamport_tpu.core.state import DenseTopology
    from chandy_lamport_tpu.ops.tick import TickKernel

    with pytest.raises(ValueError, match="position-addressable"):
        TickKernel(DenseTopology(spec), cfg, GoExactJaxDelay(7),
                   exact_impl="wave")


def test_block_receive_times_match_sequential_draws():
    """The sampler-level contract the wave stands on: for the hash
    sampler, block_receive_times at offsets [0..n) + advance_draws(n)
    reproduces n sequential draw() calls exactly — in any service order."""
    d = HashJaxDelay(seed=1234)
    st = d.init_state()
    seq = []
    cur = st
    for _ in range(17):
        rt, cur = d.draw(cur, 100)
        seq.append(int(rt))
    perm = np.random.RandomState(0).permutation(17)
    blk = d.block_receive_times(st, 100, np.asarray(perm, np.int32))
    assert [int(x) for x in np.asarray(blk)] == [seq[i] for i in perm]
    adv = d.advance_draws(st, 17)
    for a, b in zip(jax.tree_util.tree_leaves(adv),
                    jax.tree_util.tree_leaves(cur)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
