"""SimConfig.window_dtype="uint16": the window-counter planes stored
modulo 2^16. Decode must be identical to the int32 planes (the counters
only ever matter through window LENGTHS, bounded by L, and log positions
mod L with L | 2^16) — across the sync kernel, the exact kernel, and a
synthetic counter wrap."""

from types import SimpleNamespace

import jax
import numpy as np
import pytest

from chandy_lamport_tpu.api import run_events
from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import DenseTopology, recorded_window
from chandy_lamport_tpu.models.delay import GoExactDelay
from chandy_lamport_tpu.models.workloads import (
    erdos_renyi,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner
from chandy_lamport_tpu.utils.randgen import (
    random_script,
    random_strongly_connected,
)


def test_config_rejects_bad_log_capacity():
    with pytest.raises(ValueError, match="power of two"):
        SimConfig(window_dtype="uint16", max_recorded=48)
    SimConfig(window_dtype="uint16", max_recorded=64)  # fine


@pytest.mark.slow  # ~10 s; test_uint16_exact_scheduler_vs_parity pins uint16
# windows against the parity oracle (strictly stronger per-window) and
# test_recorded_window_decodes_across_uint16_wrap pins the wrap — tier-1
def test_uint16_matches_int32_sync_storm():
    spec = erdos_renyi(24, 2.5, seed=6, tokens=80)
    finals = []
    for wd in ("int32", "uint16"):
        cfg = SimConfig(queue_capacity=32, max_recorded=32,
                        max_snapshots=8, window_dtype=wd)
        runner = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=2,
                               scheduler="sync")
        prog = storm_program(runner.topo, phases=10, amount=1,
                             snapshot_phases=staggered_snapshots(runner.topo, 3))
        final = jax.device_get(runner.run_storm(runner.init_batch(), prog))
        assert int(final.error.sum()) == 0
        finals.append((runner.topo, final))
    (topo, a), (_, b) = finals
    np.testing.assert_array_equal(a.tokens, b.tokens)
    for lane in range(2):
        la = jax.tree_util.tree_map(lambda x: x[lane], a)
        lb = jax.tree_util.tree_map(lambda x: x[lane], b)
        for sid in range(int(la.next_sid)):
            for e in range(topo.e):
                assert (recorded_window(la, sid, e)
                        == recorded_window(lb, sid, e)), (lane, sid, e)


@pytest.mark.parametrize("case_seed", [0, 1])
def test_uint16_exact_scheduler_vs_parity(case_seed):
    import random

    rng = random.Random(7700 + case_seed)
    topo = random_strongly_connected(rng, rng.randrange(3, 10))
    events = random_script(rng, topo, rng.randrange(12, 35))
    cfg = SimConfig(queue_capacity=64, max_recorded=64,
                    window_dtype="uint16")
    p_snaps, p_sim = run_events("parity", topo, events,
                                GoExactDelay(55 + case_seed))
    d_snaps, d_sim = run_events("jax", topo, events,
                                GoExactDelay(55 + case_seed), cfg)
    assert p_sim.node_tokens() == d_sim.node_tokens()
    assert len(p_snaps) == len(d_snaps)
    for ps, ds in zip(p_snaps, d_snaps):
        assert ps.token_map == ds.token_map
        assert ps.messages == ds.messages


def test_uint16_checkpoint_roundtrip(tmp_path):
    """Checkpoint round-trip preserves the uint16 window planes (dtype is
    validated leaf-by-leaf on restore)."""
    from chandy_lamport_tpu.utils.checkpoint import load_state, save_state

    spec = erdos_renyi(12, 2.5, seed=2, tokens=40)
    cfg = SimConfig(queue_capacity=16, max_recorded=32, max_snapshots=4,
                    window_dtype="uint16")
    runner = BatchedRunner(spec, cfg, FixedJaxDelay(2), batch=2,
                           scheduler="sync")
    prog = storm_program(runner.topo, phases=6, amount=1,
                         snapshot_phases=staggered_snapshots(runner.topo, 2))
    final = jax.device_get(runner.run_storm(runner.init_batch(), prog))
    path = str(tmp_path / "w16.npz")
    save_state(path, final, {"note": "uint16 windows"})
    restored, meta = load_state(path, runner.init_batch())
    assert meta["note"] == "uint16 windows"
    assert np.dtype(np.asarray(restored.rec_start).dtype) == np.uint16
    np.testing.assert_array_equal(np.asarray(restored.rec_start),
                                  np.asarray(final.rec_start))
    np.testing.assert_array_equal(np.asarray(restored.tokens),
                                  np.asarray(final.tokens))


def test_recorded_window_decodes_across_uint16_wrap():
    """A window straddling the 2^16 counter wrap decodes the same arrivals
    an absolute counter would: length = (end - start) mod 2^16, positions
    (start + k) mod L == absolute j mod L since L | 2^16."""
    L = 16
    true_start, length = 65533, 5        # absolute counters 65533..65538
    amounts = [7, 11, 13, 17, 19]
    log = np.zeros((L, 1), np.int32)
    for k, amt in enumerate(amounts):
        log[(true_start + k) % L, 0] = amt
    host = SimpleNamespace(
        log_amt=log,
        rec_cnt=np.array([true_start + length], np.int32),
        recording=np.array([[False]]),
        rec_start=np.array([[true_start & 0xFFFF]], np.uint16),
        rec_end=np.array([[(true_start + length) & 0xFFFF]], np.uint16),
    )
    assert int(host.rec_end[0, 0]) < int(host.rec_start[0, 0])  # wrapped
    assert recorded_window(host, 0, 0) == amounts
    # live window (still recording): end falls back to the i32 rec_cnt
    host.recording[0, 0] = True
    assert recorded_window(host, 0, 0) == amounts
