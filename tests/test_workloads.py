"""Workload generators + storm execution: strong connectivity, bulk-send
equivalence with per-event injection, and per-lane invariants at small scale."""

import jax
import numpy as np

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import PassTokenEvent, TickEvent
from chandy_lamport_tpu.core.state import DenseTopology, decode_snapshot
from chandy_lamport_tpu.models.delay import FixedDelay
from chandy_lamport_tpu.models.workloads import (
    StormProgram,
    erdos_renyi,
    ring_topology,
    scale_free,
    staggered_snapshots,
    storm_program,
)
from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, UniformJaxDelay
from chandy_lamport_tpu.parallel.batch import BatchedRunner


def _reachable(topo_spec):
    ids = [n for n, _ in topo_spec.nodes]
    adj = {n: [] for n in ids}
    for s, d in topo_spec.links:
        adj[s].append(d)
    seen, stack = {ids[0]}, [ids[0]]
    while stack:
        for d in adj[stack.pop()]:
            if d not in seen:
                seen.add(d)
                stack.append(d)
    return len(seen) == len(ids)


def test_generators_strongly_connected():
    for spec in (ring_topology(17), erdos_renyi(32, 3.0, seed=1),
                 scale_free(32, 2, seed=2)):
        assert _reachable(spec)
        # ring embedding makes every node reachable from every other:
        # rotate start by checking the reverse direction too
        rev = type(spec)(spec.nodes, [(d, s) for s, d in spec.links])
        # (reverse reachability of the ring holds because the ring is a cycle)
        assert _reachable(spec)


def test_storm_matches_per_event_injection_fixed_delay():
    """One storm phase under a fixed delay must equal the same sends issued
    as individual events plus a tick (delay stream is order-free there)."""
    spec = ring_topology(6, tokens=50)
    runner = BatchedRunner(spec, SimConfig(), FixedJaxDelay(2), batch=2)
    topo = runner.topo
    prog = storm_program(topo, phases=3, amount=2)
    storm_final = jax.device_get(
        runner.run_storm(runner.init_batch(), prog, drain=False))

    # equivalent explicit event script on the single-instance backend
    from chandy_lamport_tpu.api import run_events
    events = []
    amounts = np.asarray(prog.amounts)
    for ph in range(amounts.shape[0]):
        for e in np.nonzero(amounts[ph])[0]:
            events.append(PassTokenEvent(topo.ids[int(topo.edge_src[e])],
                                         topo.ids[int(topo.edge_dst[e])],
                                         int(amounts[ph, e])))
        events.append(TickEvent(1))
    from chandy_lamport_tpu.core.dense import DenseSim
    sim = DenseSim(spec, FixedDelay(2), SimConfig())
    for ev in events:
        sim.process_event(ev)
    single = jax.device_get(sim.state)

    for i in range(2):
        np.testing.assert_array_equal(storm_final.tokens[i], single.tokens)
        np.testing.assert_array_equal(storm_final.q_len[i], single.q_len)
        np.testing.assert_array_equal(storm_final.q_meta[i], single.q_meta)


import pytest


@pytest.mark.parametrize("scheduler", [
    # the exact leg costs ~11 s of compile; sync keeps the invariants in
    # tier-1 and every tier-1 golden differential runs the exact sampler
    pytest.param("exact", marks=pytest.mark.slow), "sync"])
def test_storm_scale_invariants(scheduler):
    spec = scale_free(24, 2, seed=5, tokens=200)
    b = 4
    runner = BatchedRunner(spec, SimConfig(queue_capacity=32, max_recorded=64),
                           UniformJaxDelay(seed=11), batch=b,
                           scheduler=scheduler)
    topo = runner.topo
    prog = storm_program(topo, phases=30, amount=1,
                         snapshot_phases=staggered_snapshots(topo, 6, 2, 3))
    host = jax.device_get(runner.run_storm(runner.init_batch(), prog))

    assert int(host.error.sum()) == 0
    total0 = int(topo.tokens0.sum())
    for i in range(b):
        lane = jax.tree_util.tree_map(lambda x: x[i], host)
        assert int(lane.q_len.sum()) == 0
        assert int(lane.tokens.sum()) == total0
        assert int(lane.next_sid) == 6
        for sid in range(6):
            assert int(lane.completed[sid]) == topo.n
            snap = decode_snapshot(topo, lane, sid)
            assert (sum(snap.token_map.values())
                    + sum(m.message.data for m in snap.messages) == total0)


@pytest.mark.slow  # ~12 s; gather-vs-mask engine equality in test_queue_engine stays tier-1
def test_sync_scheduler_deterministic():
    """Same seed -> bit-identical final state across independent runs."""
    spec = erdos_renyi(16, 3.0, seed=8, tokens=100)
    outs = []
    for _ in range(2):
        runner = BatchedRunner(spec, SimConfig(), UniformJaxDelay(seed=21),
                               batch=4, scheduler="sync")
        prog = storm_program(runner.topo, phases=12, amount=1,
                             snapshot_phases=staggered_snapshots(runner.topo, 3))
        outs.append(jax.device_get(runner.run_storm(runner.init_batch(), prog)))
    for a, b in zip(jax.tree_util.tree_leaves(outs[0]),
                    jax.tree_util.tree_leaves(outs[1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sync_matches_exact_token_only_traffic():
    """With no markers in flight the two schedulers deliver the same heads
    every tick (deliveries never unlock same-tick eligibility), so pure
    token traffic must produce identical states under a shared delay
    stream."""
    spec = ring_topology(8, tokens=100)
    results = []
    for scheduler in ("exact", "sync"):
        runner = BatchedRunner(spec, SimConfig(), FixedJaxDelay(3), batch=2,
                               scheduler=scheduler)
        prog = storm_program(runner.topo, phases=10, amount=2)
        final = runner.run_storm(runner.init_batch(), prog, drain=False)
        results.append(jax.device_get(final))
    for a, b in zip(jax.tree_util.tree_leaves(results[0]._replace(delay_state=())),
                    jax.tree_util.tree_leaves(results[1]._replace(delay_state=()))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
