#!/usr/bin/env python
"""Kernel cost breakdown for the bench workload (BASELINE.md §profiling).

Compiles the benchmark's storm-phase pieces separately and reports XLA
cost-analysis estimates (flops / bytes accessed) plus measured wall-clock per
component, so the dominant op of the tick is identified even without a
trace viewer. Use CLSIM_PLATFORM=cpu off-TPU.

``--telemetry FILE`` switches to telemetry mode: instead of compiling
kernels, summarize a schema-versioned JSONL stream written by the CLI's
``--telemetry`` flags or ``trace`` subcommand (utils/tracing.
TelemetryWriter) — per-kind record counts, run-row headlines, and the
decoded-event histogram.

``--bench-rows FILE`` switches to bench-row mode: read a JSONL stream of
bench worker rows (one ``bench.py`` JSON line per row, as collected by the
ladder sweeps) and print kernel-engine comparison curves — per graph-size
rung, throughput under ``kernel_engine=xla`` vs ``pallas`` side by side
with the speedup, so the Pallas claim is read off measured rows instead of
asserted.

``--cost`` switches to budget mode: print the static per-arm cost rows
pinned in ``tools/staticcheck/cost_budgets.json`` (modeled FLOPs / HBM
bytes / collective traffic per compiled engine arm) and cross-check the
graphshard dense-vs-sparse collective bytes against the analytic
``utils/metrics.comm_bytes_model`` at the audit fixture's cut.

``--slo-ladder FILE`` switches to fleet-ladder mode: read a JSONL stream
of ``bench --fleet`` rows and print the serving-fleet SLO ladder — the
worker-count knee curve (served jobs/s, scaling, goodput, latency
percentiles, the WAL conservation verdict) plus the degraded-mode rows
(injected worker SIGKILLs) with their throughput retention against the
clean rung at the same worker count.

Usage: python tools/analyze.py [--nodes N] [--batch B] [--scheduler sync]
       python tools/analyze.py --telemetry runs.jsonl
       python tools/analyze.py --bench-rows rows.jsonl
       python tools/analyze.py --slo-ladder fleet.jsonl
       python tools/analyze.py --cost
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def analyze_telemetry(path: str) -> None:
    """Summarize a TelemetryWriter JSONL stream: per-kind counts, run-row
    headlines, and the event histogram (torn trailing lines are skipped by
    the reader; a newer schema version fails loudly there)."""
    from collections import Counter

    from chandy_lamport_tpu.utils.tracing import read_telemetry

    records = read_telemetry(path)
    if not records:
        print(f"{path}: no telemetry records")
        return
    kinds = Counter(r["kind"] for r in records)
    print(f"{path}: {len(records)} records "
          f"(schema {records[0]['schema']})")
    for kind, cnt in sorted(kinds.items()):
        print(f"  {kind:<16} {cnt}")
    # run rows: one headline per row, whatever kind produced it (the memo
    # plane's hit/coalesce/fast-forward books ride along when present,
    # and the prefix plane's fork books beside them)
    run_keys = ("value", "unit", "trace_events", "trace_dropped",
                "error_bits", "jobs_done", "snapshots", "wall_seconds",
                "memo", "cache_hits", "coalesced_jobs", "ff_skipped_ticks",
                "shadow_checks", "memo_hit_rate", "effective_jobs_per_sec",
                "prefix_hits", "forked_jobs", "fork_depth_mean",
                "prefix_evictions", "prefix_speedup")
    for r in records:
        if not r["kind"].endswith("_run"):
            continue
        fields = {k: r[k] for k in run_keys if k in r}
        print(f"  {r['kind']}: " + ", ".join(
            f"{k}={v}" for k, v in fields.items()))
        hist = r.get("fork_depth_hist")
        if hist:
            bars = ", ".join(f"d{d}:{hist[d]}"
                             for d in sorted(hist, key=int))
            print(f"    fork depths: {bars}")
    events = [r for r in records if r["kind"] == "event"]
    if events:
        hist = Counter(e["event"] for e in events)
        ticks = [e["tick"] for e in events]
        print(f"  event histogram ({len(events)} events, "
              f"ticks {min(ticks)}..{max(ticks)}):")
        for name, cnt in hist.most_common():
            print(f"    {name:<16} {cnt}")
    jobs = [r for r in records if r["kind"] == "stream_job"]
    if jobs:
        errored = [j for j in jobs if j.get("error")]
        served = [j for j in jobs if j.get("served_from")]
        line = (f"  stream jobs: {len(jobs)} harvested, "
                f"{len(errored)} errored")
        forked = [j for j in served
                  if str(j["served_from"]).startswith("prefix:")]
        served = [j for j in served if j not in forked]
        if served:
            from_cache = sum(1 for j in served
                             if j["served_from"] == "cache")
            line += (f", {len(served)} memo-served "
                     f"({from_cache} cache, "
                     f"{len(served) - from_cache} coalesced)")
        if forked:
            # served_from="prefix:<depth>" provenance rows: hit rate over
            # the whole harvest + the depth histogram of the forks
            depths = Counter(int(str(j["served_from"]).split(":")[1])
                             for j in forked)
            bars = ", ".join(f"d{d}:{depths[d]}" for d in sorted(depths))
            line += (f", {len(forked)} prefix-forked "
                     f"(hit rate {len(forked) / len(jobs):.2f}; {bars})")
        print(line)


def analyze_bench_rows(path: str) -> None:
    """Kernel-engine comparison curves from bench worker rows (JSONL, one
    bench.py JSON line per row). Rows are grouped by the workload shape
    (graph family, nodes, batch, scheduler, platform); within each group
    the best row per kernel_engine is kept (repeat sweeps appear as
    multiple rows) and xla/pallas are printed side by side. Unparseable
    lines are counted and skipped — sweep logs interleave stderr noise."""
    import json

    rows, skipped = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(r, dict) and "value" in r and "kernel_engine" in r:
                rows.append(r)
            else:
                skipped += 1
    if not rows:
        print(f"{path}: no bench rows with a kernel_engine field"
              + (f" ({skipped} lines skipped)" if skipped else ""))
        return
    groups = {}
    for r in rows:
        key = (r.get("graph", "?"), r.get("nodes", 0), r.get("batch", 0),
               r.get("scheduler", "?"), r.get("platform", "?"))
        groups.setdefault(key, {})
        eng = r["kernel_engine"]
        best = groups[key].get(eng)
        if best is None or r["value"] > best["value"]:
            groups[key][eng] = r
    print(f"{path}: {len(rows)} bench rows, {len(groups)} workload "
          f"shapes" + (f" ({skipped} lines skipped)" if skipped else ""))
    unit = rows[0].get("unit", "node-ticks/s")
    print(f"  {'graph':<6} {'nodes':>6} {'batch':>6} {'sched':<6} "
          f"{'platform':<8} {'xla':>12} {'pallas':>12} {'pallas/xla':>10}")
    for key in sorted(groups):
        graph, nodes, batch, sched, plat = key
        by_eng = groups[key]
        x = by_eng.get("xla")
        pl = by_eng.get("pallas")
        ratio = (f"{pl['value'] / x['value']:9.2f}x"
                 if x and pl and x["value"] else f"{'—':>10}")
        fmt = lambda r: f"{r['value']:12.3g}" if r else f"{'—':>12}"
        print(f"  {graph:<6} {nodes:>6} {batch:>6} {sched:<6} "
              f"{plat:<8} {fmt(x)} {fmt(pl)} {ratio}")
    print(f"  (value = {unit}; best row per engine per shape; 'auto' rows "
          "appear under their RESOLVED engine)")


def analyze_slo_ladder(path: str) -> None:
    """The serving-fleet SLO ladder from ``bench --fleet`` rows (JSONL,
    one bench.py JSON line per row). Rows are grouped by workload shape
    (graph, nodes, batch, requests, rate); within a group the CLEAN rows
    (no injected crashes) are sorted by worker count and printed as the
    knee curve — served jobs/s, scaling vs the 1-worker rung, goodput and
    the latency percentiles — followed by the DEGRADED rows (injected
    SIGKILLs) under their clean baseline with the takeover/restart books
    and the throughput retention, which is the graceful-degradation
    number the fleet claims."""
    import json

    rows, skipped = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(r, dict) and \
                    r.get("metric") == "fleet_served_jobs_per_sec":
                rows.append(r)
            else:
                skipped += 1
    if not rows:
        print(f"{path}: no fleet bench rows"
              + (f" ({skipped} lines skipped)" if skipped else ""))
        return
    groups = {}
    for r in rows:
        key = (r.get("graph", "?"), r.get("nodes", 0), r.get("batch", 0),
               r.get("requests", 0), r.get("rate", 0.0))
        groups.setdefault(key, []).append(r)
    print(f"{path}: {len(rows)} fleet rows, {len(groups)} workload "
          f"shapes" + (f" ({skipped} lines skipped)" if skipped else ""))
    for key in sorted(groups):
        graph, nodes, batch, reqs, rate = key
        clean = sorted((r for r in groups[key]
                        if not r.get("crashes_injected")),
                       key=lambda r: r.get("workers", 0))
        degraded = sorted((r for r in groups[key]
                           if r.get("crashes_injected")),
                          key=lambda r: r.get("workers", 0))
        print(f"  {graph} N={nodes} B={batch}, {reqs} requests at "
              f"rate {rate}/step:")
        base = clean[0]["value"] if clean and clean[0]["value"] else None
        print(f"    {'workers':>7} {'jobs/s':>8} {'x1-worker':>9} "
              f"{'goodput':>7} {'p50 s':>7} {'p99 s':>7} {'audit':>6}")
        for r in clean:
            scale = (f"{r['value'] / base:8.2f}x" if base else f"{'—':>9}")
            audit = ("ok" if not r.get("audit_lost")
                     and not r.get("audit_double_served") else "FAIL")
            print(f"    {r.get('workers', 0):>7} {r['value']:>8.2f} "
                  f"{scale} {r.get('goodput', 0.0):>7.2f} "
                  f"{_lat(r, 'lat_p50_s')} {_lat(r, 'lat_p99_s')} "
                  f"{audit:>6}")
        for r in degraded:
            peer = next((c for c in clean
                         if c.get("workers") == r.get("workers")), None)
            keep = (f"{100.0 * r['value'] / peer['value']:.0f}% of clean"
                    if peer and peer["value"] else "no clean peer")
            audit = ("ok" if not r.get("audit_lost")
                     and not r.get("audit_double_served") else "FAIL")
            print(f"    {r.get('workers', 0):>7} {r['value']:>8.2f} "
                  f"  degraded: {r.get('crashes_injected', 0)} kill(s), "
                  f"{r.get('worker_deaths', 0)} death(s), "
                  f"{r.get('takeovers', 0)} takeover(s), "
                  f"{r.get('restarts', 0)} restart(s); {keep}; "
                  f"goodput {r.get('goodput', 0.0):.2f}; audit {audit}")
    print("  (value = served jobs/s; audit = WAL conservation: lost=0 "
          "AND double_served=0)")


def _lat(r: dict, key: str) -> str:
    v = r.get(key)
    return f"{v:7.2f}" if isinstance(v, (int, float)) else f"{'—':>7}"


def analyze_cost() -> None:
    """Modeled-cost comparison across the engine knob matrix, read off the
    pinned ``tools/staticcheck/cost_budgets.json`` rows (no jax, no
    compile: the budgets ARE the measurements, re-pinned per commit).
    The graphshard arms get a cross-check: the HLO-measured
    sparse-over-dense collective-byte ratio is printed next to the
    analytic ``comm_bytes_model`` ratio recomputed for the audit fixture
    (erdos_renyi(16, 2.5, seed=11), P=4) — the two models should agree on
    which engine moves fewer bytes and roughly by how much."""
    from tools.staticcheck.hlo_cost import BUDGETS_PATH, load_budgets

    entries, jaxver = load_budgets()
    if not entries:
        print(f"{BUDGETS_PATH}: no cost budgets — run "
              f"`python -m tools.staticcheck --plane cost "
              f"--budgets-update`")
        return
    print(f"{BUDGETS_PATH}: {len(entries)} arms (pinned under jax "
          f"{jaxver})")
    print(f"  {'arm':<44} {'flops':>10} {'bytes':>10} {'coll':>5} "
          f"{'collB':>7} {'gather':>6} {'scat':>5} {'fus':>5}")
    for key in sorted(entries):
        e = entries[key]
        print(f"  {key:<44} {e.get('flops', 0):>10.3g} "
              f"{e.get('bytes_accessed', 0):>10.3g} "
              f"{int(e.get('collective_count', 0)):>5} "
              f"{int(e.get('collective_bytes', 0)):>7} "
              f"{int(e.get('gather_count', 0)):>6} "
              f"{int(e.get('scatter_count', 0)):>5} "
              f"{int(e.get('fusion_count', 0)):>5}")

    fused_arms = {k: e for k, e in entries.items()
                  if k.startswith("tick.fused.") and "hbm_model_bytes" in e}
    if fused_arms:
        print(f"\nfused megatick HBM cross-check (kernels/megatick."
              f"hbm_round_trip_model, bytes per K-tick dispatch; the "
              f"split model is a per-tick carry round-trip FLOOR).\n"
              f"Resident arms gate at <=0.5 (carry crosses HBM once per "
              f"dispatch, not once per tick); TILED arms at <=0.55 — the "
              f"[E, C] ring planes leave the resident set and re-cross "
              f"HBM once per STEP (2*ring*(K+1) at K=4), trading that "
              f"traffic for shapes past the VMEM budget:")
        for key in sorted(fused_arms):
            # split never tiles: a tiled fused arm anchors against the
            # same-config plain megasplit twin
            tiled = key.startswith("tick.fused.tiled.")
            split_key = key.replace(
                "tick.fused.tiled." if tiled else "tick.fused.",
                "tick.megasplit.")
            split = entries.get(split_key)
            if not (split and split.get("hbm_model_bytes")):
                continue
            f_b = fused_arms[key]["hbm_model_bytes"]
            s_b = split["hbm_model_bytes"]
            ratio = f_b / s_b
            gate = 0.55 if tiled else 0.5
            side = "tiled" if tiled else "fused"
            print(f"  {key:<44} {side} {int(f_b):>7} B vs split "
                  f"{int(s_b):>7} B  ({side}/split {ratio:.3f}"
                  f"{f', <={gate} OK' if ratio <= gate else ''})")

    dense = entries.get("graphshard.dispatch.comm=dense")
    sparse = entries.get("graphshard.dispatch.comm=sparse")
    if not (dense and sparse and dense.get("collective_bytes")):
        print("  (graphshard dense/sparse arms not pinned — no comm "
              "cross-check)")
        return
    hlo_ratio = sparse["collective_bytes"] / dense["collective_bytes"]
    print(f"\ngraphshard comm cross-check (audit fixture: "
          f"erdos_renyi(16, 2.5, seed=11), P=4):")
    print(f"  HLO collective bytes/dispatch: dense "
          f"{int(dense['collective_bytes'])} B, sparse "
          f"{int(sparse['collective_bytes'])} B "
          f"(sparse/dense {hlo_ratio:.3f})")
    try:
        from chandy_lamport_tpu.config import SimConfig
        from chandy_lamport_tpu.core.state import DenseTopology
        from chandy_lamport_tpu.models.workloads import erdos_renyi
        from chandy_lamport_tpu.parallel.graphshard import shard_topology
        from chandy_lamport_tpu.utils.metrics import comm_bytes_model
    except Exception as exc:  # jax-less environment: table still useful
        print(f"  (analytic comm_bytes_model unavailable here: {exc})")
        return
    topo = DenseTopology(erdos_renyi(16, 2.5, seed=11, tokens=40))
    cfg = SimConfig.for_workload(snapshots=2, max_recorded=32)
    _, _, bt = shard_topology(topo, 4, incidence=False)
    m = comm_bytes_model(topo.n, cfg.max_snapshots, 4, bt.halo,
                         cut_edges=bt.cut_edges, cut_rows=bt.cut_rows)
    print(f"  comm_bytes_model bytes/tick:   dense "
          f"{m['dense_bytes_per_tick']} B, sparse "
          f"{m['sparse_bytes_per_tick']} B "
          f"(sparse/dense {m['sparse_over_dense']:.3f})")
    agree = ((hlo_ratio < 1.0) == (m["sparse_over_dense"] < 1.0))
    print(f"  models {'AGREE' if agree else 'DISAGREE'} on the cheaper "
          f"engine at this cut (halo {m['halo_rows']} rows, "
          f"{m['cut_edges']} cut edges); HLO counts whole-dispatch "
          f"collectives, the analytic model one steady tick — compare "
          f"ratios, not magnitudes")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=1024)
    p.add_argument("--attach", type=int, default=2)
    p.add_argument("--batch", type=int, default=2048)
    p.add_argument("--snapshots", type=int, default=8)
    p.add_argument("--scheduler", choices=["sync", "exact"], default="sync")
    p.add_argument("--repeats", type=int, default=20)
    p.add_argument("--telemetry", metavar="FILE",
                   help="summarize this JSONL telemetry stream instead of "
                        "running the kernel cost analysis")
    p.add_argument("--bench-rows", metavar="FILE",
                   help="print kernel-engine comparison curves from this "
                        "JSONL stream of bench worker rows instead of "
                        "running the kernel cost analysis")
    p.add_argument("--slo-ladder", metavar="FILE",
                   help="print the serving-fleet SLO ladder (worker-count "
                        "knee curve + degraded-mode retention) from this "
                        "JSONL stream of bench --fleet rows")
    p.add_argument("--cost", action="store_true",
                   help="print the pinned static cost rows per engine arm "
                        "(tools/staticcheck/cost_budgets.json) plus the "
                        "graphshard dense-vs-sparse comm cross-check "
                        "against utils/metrics.comm_bytes_model")
    args = p.parse_args()

    if args.telemetry:
        return analyze_telemetry(args.telemetry)
    if args.bench_rows:
        return analyze_bench_rows(args.bench_rows)
    if args.slo_ladder:
        return analyze_slo_ladder(args.slo_ladder)
    if args.cost:
        return analyze_cost()

    platform = os.environ.get("CLSIM_PLATFORM")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp
    import numpy as np

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import scale_free
    from chandy_lamport_tpu.ops.delay_jax import UniformJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.metrics import instance_footprint_bytes

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})")

    spec = scale_free(args.nodes, args.attach, seed=3, tokens=100)
    cfg = SimConfig(queue_capacity=16, max_snapshots=max(8, args.snapshots),
                    max_recorded=16)
    runner = BatchedRunner(spec, cfg, UniformJaxDelay(seed=17),
                           batch=args.batch, scheduler=args.scheduler)
    topo = runner.topo
    per = instance_footprint_bytes(topo.n, topo.e, cfg)
    print(f"graph: N={topo.n} E={topo.e} D={topo.d}; "
          f"footprint {per / 1e6:.3f} MB/instance, "
          f"{per * args.batch / 1e9:.2f} GB batch")

    # device-resident state: init_batch() is host numpy, and timing a jit
    # call on it measures the host->device transfer (16s at bench shape
    # through the remote tunnel), not the kernel
    state = runner.init_batch_device()
    jax.block_until_ready(state)
    amounts = jnp.ones((topo.e,), jnp.int32)
    snaps = jnp.full((args.snapshots,), -1, jnp.int32)
    snaps_live = jnp.arange(args.snapshots, dtype=jnp.int32)

    components = {
        "tick_only": lambda s: jax.vmap(runner._tick_fn)(s),
        "bulk_send_only": lambda s: jax.vmap(
            lambda s: runner.kernel._bulk_send(s, amounts))(s),
        "full_phase_no_snap": lambda s: jax.vmap(
            runner.storm_phase, in_axes=(0, None, None))(s, amounts, snaps),
        "full_phase_with_snaps": lambda s: jax.vmap(
            runner.storm_phase, in_axes=(0, None, None))(s, amounts, snaps_live),
    }

    results = {}
    for name, fn in components.items():
        jfn = jax.jit(fn)
        lowered = jfn.lower(state)
        compiled = lowered.compile()
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            flops = ca.get("flops", float("nan"))
            bytes_acc = ca.get("bytes accessed", float("nan"))
        except Exception as exc:  # cost analysis is backend-dependent
            flops = bytes_acc = float("nan")
            print(f"  ({name}: no cost analysis: {exc})")
        out = jfn(state)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.repeats):
            out = jfn(state)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / args.repeats
        results[name] = (dt, flops, bytes_acc)
        node_ticks = args.batch * topo.n
        print(f"{name:24s} {dt * 1e3:8.2f} ms "
              f"{flops / 1e9:10.2f} GF {bytes_acc / 1e9:10.2f} GB "
              f"-> {node_ticks / dt / 1e6:8.1f}M node-ticks/s if tick-bound")

    base = results["tick_only"][0]
    send = results["bulk_send_only"][0]
    phase = results["full_phase_no_snap"][0]
    snapped = results["full_phase_with_snaps"][0]
    print(f"\nbreakdown: tick {base * 1e3:.2f} ms, send {send * 1e3:.2f} ms, "
          f"phase overhead {(phase - base - send) * 1e3:.2f} ms, "
          f"snapshot-initiation surcharge {(snapped - phase) * 1e3:.2f} ms")

    # graph-sharded comm model at this shape: partition-time boundary
    # tables give the measured cut, so the dense-vs-sparse byte curves
    # (utils/metrics.comm_bytes_model) need no mesh or device
    from chandy_lamport_tpu.parallel.graphshard import shard_topology
    from chandy_lamport_tpu.utils.metrics import comm_bytes_model

    shard_counts = [p for p in (2, 4, 8) if topo.n % p == 0]
    if shard_counts:
        print("\ngraphshard comm model (per-shard bytes/tick, "
              "dense full-plane vs sparse halo exchange):")
        for p_ in shard_counts:
            _, _, bt = shard_topology(runner.topo, p_, incidence=False)
            m = comm_bytes_model(topo.n, cfg.max_snapshots, p_, bt.halo,
                                 cut_edges=bt.cut_edges,
                                 cut_rows=bt.cut_rows)
            print(f"  P={p_}: dense {m['dense_bytes_per_tick']:>8} B  "
                  f"sparse {m['sparse_bytes_per_tick']:>8} B  "
                  f"(ratio {m['sparse_over_dense']:.3f}, "
                  f"halo {m['halo_rows']}, cut {m['cut_edges']} edges)")


if __name__ == "__main__":
    main()
