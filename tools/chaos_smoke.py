#!/usr/bin/env python
"""Chaos smoke: inject every fault class once, demand recovery, fast.

The tier-1-safe slice of the robustness story (ISSUE 3): one small batched
storm per fault class under the deterministic adversary (models/faults.py),
asserting after each that the framework RECOVERED rather than merely
survived —

  * the injected class actually fired (fault_counts evidence, per class);
  * the adversary's books balance: the skew-adjusted conservation delta
    (utils/metrics.conservation_delta) is exactly zero — injected faults
    move tokens, they never leak them;
  * no UNQUARANTINED error bit anywhere: scenarios expected to stay
    healthy end with zero error lanes; the deliberately-unrecoverable
    scenario (lossy crash before any completed snapshot) ends with every
    injured lane frozen by quarantine, decoded bits surfaced, and no bit
    other than the expected ERR_FAULT_UNRECOVERED;
  * snapshot-rollback recovery works: a lossy crash AFTER a completed
    Chandy-Lamport snapshot restores from the snapshot's frozen cut and
    finishes the storm with zero error bits.

Shapes are deliberately tiny (ring-8 / scale-free-16, batch 4) so the whole
battery — compile included — lands well under 60 s on CPU; this is the
"did robustness regress" canary, not a soak (tools/soak.py is the battery).

The serve-fleet scenarios (clsim-serve-ha, serving/fleet.py) extend the
battery to PROCESS-level chaos: SIGKILL a worker mid-flight and demand
lease takeover with zero requests lost or double-served and every served
summary bit-identical to a solo ``run_stream`` of that request; crash
every holder of one request until the supervisor quarantines it as
poison with the full provenance trail; and overload a one-worker fleet
until deadline-aware shedding drops exactly the predicted victims.
``--fleet-only`` runs just that trio (the tier-1 slice — the rest of the
battery is the slow marker).

The prefix-fork scenario (ISSUE 20, memo="prefix") drills the
speculative-fork plane under live faults: a near-duplicate queue forked
from cached prefix checkpoints with the message-plane adversary armed
must byte-match its cold memo-off re-execution under an every-fork
shadow audit with balanced books (prefix_hits == forked_jobs), and a
POISONED PrefixCache (checkpointed token state tampered on disk) must
be refused loudly by that audit with the named PrefixCacheError, never
served silently. ``--prefix-only`` runs just it (tier-1 slice).

Usage: python tools/chaos_smoke.py [--seed S] [--fleet-only|--prefix-only]
Prints one verdict line per scenario (stderr) + a JSON summary (stdout);
exit 0 iff every scenario held every invariant.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def fleet_scenarios(seed: int):
    """The serve-fleet chaos trio (module docstring): returns (rows, ok).
    Runs REAL multiprocessing workers against a shared WAL spool in a
    throwaway directory; scenario A pays one jitted engine per worker,
    B and C ride the jax-free null executor."""
    import tempfile

    from chandy_lamport_tpu.models.workloads import (
        ring_topology,
        serve_workload,
    )
    from chandy_lamport_tpu.serving.admission import shed_order
    from chandy_lamport_tpu.serving.fleet import fleet_run, recipe_runner

    rows, ok = [], True
    spec = ring_topology(8, tokens=16)
    reqs = serve_workload(spec, 5, seed=seed + 8, rate=2.0, tenants=2,
                          priorities=3, max_phases=4, deadline_slack=(8, 64))
    d = tempfile.mkdtemp(prefix="clsim-fleet-chaos-")

    # -- A: SIGKILL a real worker the moment it leases job 2 (once,
    #    fleet-wide). The lease must expire, the survivor or the
    #    restarted worker must take over, and at the end the WAL audit
    #    must balance: nothing lost, nothing double-served, and every
    #    served summary bit-identical to a solo run_stream of that
    #    request (singleton pools pin the content-rank, fleet.py).
    recipe = {"kind": "ring-stream", "n": 8, "tokens": 16, "snapshots": 2,
              "max_recorded": 32, "batch": 2, "scheduler": "sync",
              "memo_cache": os.path.join(d, "memo.jsonl")}
    counter = os.path.join(d, "kills")
    rep = fleet_run(reqs, spool_path=os.path.join(d, "takeover.jsonl"),
                    workers=2, recipe=recipe, lease_ttl=3.0, lease_limit=2,
                    chaos={"kill_on_job": 2, "kill_limit": 1,
                           "counter_path": counter},
                    restart_backoff=0.2, max_wall_s=120)
    solo = recipe_runner({**recipe, "memo_cache": None})
    identical = True
    for j, fs in rep["results"].items():
        pool = solo.pack_jobs([reqs[int(j)].events], content_keys=True)
        _, stream = solo.run_stream(pool, stretch=2, drain_chunk=8)
        (srow,) = solo.stream_results(stream)
        srow = {k: v for k, v in srow.items()
                if k not in ("job", "admit_step")}
        fsumm = {k: v for k, v in fs.items()
                 if k not in ("digest", "served_from")}
        identical &= fsumm == srow
    with open(counter, "r", encoding="utf-8") as f:
        kills = int(f.read().strip() or 0)
    audit = rep["audit"]
    checks = {
        "all_served": rep["served"] == len(reqs),
        "none_lost": audit["lost"] == 0,
        "none_double_served": audit["double_served"] == 0,
        "digests_intact": audit["digests_ok"],
        "worker_died": rep["books"]["worker_deaths"] >= 1,
        "lease_taken_over": rep["books"]["takeovers"] >= 1,
        "killed_exactly_once": kills == 1,
        "bit_identical_to_solo": identical,
    }
    row = {"scenario": "fleet-kill-takeover", "served": rep["served"],
           "books": {k: rep["books"][k] for k in
                     ("takeovers", "worker_deaths", "restarts")},
           "audit": audit, "checks": checks, "ok": all(checks.values())}
    ok &= row["ok"]
    rows.append(row)
    log(f"fleet-kill-takeover: {'ok' if row['ok'] else 'FAIL'} "
        f"served={rep['served']} deaths={rep['books']['worker_deaths']} "
        f"takeovers={rep['books']['takeovers']}"
        f"{'' if row['ok'] else ' checks=' + str(checks)}")

    # -- B: crash EVERY holder of job 1 (null executor — pure
    #    control-plane chaos). After max_attempts the supervisor must
    #    quarantine it as poison carrying one decoded provenance entry
    #    per burned attempt, and still serve everything else.
    rep = fleet_run(reqs, spool_path=os.path.join(d, "poison.jsonl"),
                    workers=2, recipe=None, lease_ttl=0.5, max_attempts=2,
                    lease_limit=1,
                    chaos={"kill_on_job": 1, "kill_limit": 99,
                           "counter_path": os.path.join(d, "kills-b")},
                    restart_backoff=0.1, max_wall_s=60)
    poisoned = {int(k): v for k, v in rep["poisoned"].items()}
    checks = {
        "poisoned_exactly_victim": sorted(poisoned) == [1],
        "provenance_per_attempt": bool(
            poisoned and len(poisoned[1]["errors"]) == 2
            and all("SIGKILL" in e for e in poisoned[1]["errors"])),
        "others_served": rep["served"] == len(reqs) - 1,
        "none_lost": rep["audit"]["lost"] == 0,
        "none_double_served": rep["audit"]["double_served"] == 0,
        "workers_died": rep["books"]["worker_deaths"] >= 2,
    }
    row = {"scenario": "fleet-poison-quarantine", "served": rep["served"],
           "poisoned": poisoned,
           "books": {k: rep["books"][k] for k in
                     ("takeovers", "worker_deaths", "restarts")},
           "audit": rep["audit"], "checks": checks,
           "ok": all(checks.values())}
    ok &= row["ok"]
    rows.append(row)
    log(f"fleet-poison-quarantine: {'ok' if row['ok'] else 'FAIL'} "
        f"served={rep['served']} poisoned={sorted(poisoned)}"
        f"{'' if row['ok'] else ' checks=' + str(checks)}")

    # -- C: quota pressure — six requests against a one-worker fleet
    #    whose backlog capacity is two. The four victims must be exactly
    #    admission.shed_order's prediction (lowest priority class first,
    #    most slack first within it), shed deterministically at
    #    admission time, and the books must still balance.
    shed_reqs = serve_workload(spec, 6, seed=seed + 9, rate=4.0, tenants=2,
                               priorities=3, max_phases=4,
                               deadline_slack=(8, 64))
    rep = fleet_run(shed_reqs, spool_path=os.path.join(d, "shed.jsonl"),
                    workers=1, recipe=None, lease_ttl=2.0, shed_backlog=2,
                    max_wall_s=60)
    victims = sorted(r.job for r in shed_order(shed_reqs)[:4])
    shed = sorted(int(k) for k in rep["shed"])
    checks = {
        "shed_exact_prediction": shed == victims,
        "survivors_served": rep["served"] == len(shed_reqs) - len(victims),
        "terminal_conservation": rep["served"] + len(shed)
        == len(shed_reqs),
        "none_lost": rep["audit"]["lost"] == 0,
    }
    row = {"scenario": "fleet-shed-pressure", "served": rep["served"],
           "shed": shed, "predicted": victims, "audit": rep["audit"],
           "checks": checks, "ok": all(checks.values())}
    ok &= row["ok"]
    rows.append(row)
    log(f"fleet-shed-pressure: {'ok' if row['ok'] else 'FAIL'} "
        f"served={rep['served']} shed={shed} predicted={victims}"
        f"{'' if row['ok'] else ' checks=' + str(checks)}")
    return rows, ok


def prefix_scenarios(seed: int):
    """The prefix-fork chaos drill (module docstring): returns
    (rows, ok). One near-duplicate queue (prefix_overlap traffic), the
    message-plane adversary armed on every job, driven twice through a
    memo="prefix" runner over a shared on-disk PrefixCache so the
    second drive forks every near-dup from checkpoints — then the SAME
    cache file is tampered and the next drive must refuse it."""
    import tempfile

    import jax  # noqa: F401  (imported for the side effect of config)

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.faults import JaxFaults
    from chandy_lamport_tpu.models.workloads import (
        ring_topology,
        stream_jobs,
    )
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.memocache import PrefixCacheError

    rows, ok = [], True
    ring = ring_topology(8, tokens=100)
    cfg = SimConfig.for_workload(snapshots=2, max_recorded=64)
    d = tempfile.mkdtemp(prefix="clsim-prefix-chaos-")
    cache = os.path.join(d, "prefix.jsonl")
    jcount = 12

    def build(memo):
        return BatchedRunner(
            ring, cfg, make_fast_delay("hash", 11), batch=4,
            scheduler="exact", quarantine=True,
            faults=JaxFaults(seed, drop_rate=0.05, dup_rate=0.05,
                             jitter_rate=0.05),
            memo=memo,
            prefix_cache=cache)

    jobs = stream_jobs(ring, jcount, seed=seed, base_phases=4,
                       max_phases=10, prefix_overlap=0.75)
    runner = build("prefix")
    pool = runner.pack_jobs(jobs, content_keys=True)
    # drive 1 seeds checkpoints (in-pool heat already forks followers);
    # drive 2 forks every near-dup straight from the flushed disk cache.
    # shadow_every=1: EVERY fork is re-executed cold (a batched memo-off
    # sub-pool run on the job's own pooled fault/delay identity rows —
    # the same adversary) and byte-compared inside _prefix_finalize,
    # which RAISES on any divergence. That audit is this drill's cold
    # differential; the explicit memo-off-oracle comparison on a prefix
    # pool lives in tests/test_prefix.py (tier-1 fault-free, slow
    # faulted sweep) where it guards the audit machinery itself.
    for _ in range(2):
        state, stream = runner.run_stream(pool, stretch=2, drain_chunk=8,
                                          shadow_every=1)
    sm = runner.summarize_stream(stream)
    res = {r["job"]: r for r in runner.stream_results(stream)}
    every_fork_audited = sm["shadow_checks"] >= sm["forked_jobs"]
    checks = {
        "forked": sm["forked_jobs"] > 0,
        "queue_drained": sm["jobs_done"] == jcount,
        # the books-balance invariant: host-planned forks == device-
        # admitted forks, nothing served twice or dropped
        "books_balance": sm["prefix_hits"] == sm["forked_jobs"],
        "every_fork_audited": every_fork_audited,
        "faults_fired": any(r.get("fault_events", 0) > 0
                            for r in res.values()),
        # the drive completing + every fork audited == each forked job's
        # summary byte-matched its cold re-execution (mismatch raises)
        "forks_bit_identical_to_cold": every_fork_audited,
    }
    row = {"scenario": "prefix-fork-audit",
           "forked_jobs": sm["forked_jobs"],
           "fork_depth_mean": sm["fork_depth_mean"],
           "prefix_hits": sm["prefix_hits"],
           "shadow_checks": sm["shadow_checks"],
           "checks": checks, "ok": all(checks.values())}
    ok &= row["ok"]
    rows.append(row)
    log(f"prefix-fork-audit: {'ok' if row['ok'] else 'FAIL'} "
        f"forked={sm['forked_jobs']} depth={sm['fork_depth_mean']} "
        f"shadows={sm['shadow_checks']}"
        f"{'' if row['ok'] else ' checks=' + str(checks)}")

    # -- poison the cache ON DISK: add one token to every checkpointed
    #    `tokens` leaf (valid JSON, valid schema, valid shapes — only
    #    the STATE is wrong, the hardest poisoning to catch) and demand
    #    the next drive's shadow audit refuse it by name instead of
    #    serving forks from corrupt state.
    with open(cache, "r", encoding="utf-8") as f:
        lines = [json.loads(ln) for ln in f.read().splitlines() if ln]
    import base64

    import numpy as np

    tampered = 0
    for entry in lines:
        leaf = (entry.get("ckpt") or {}).get("leaves", {}).get("tokens")
        if leaf is None:
            continue
        arr = np.frombuffer(base64.b64decode(leaf["b"]),
                            dtype=np.dtype(leaf["d"])).copy()
        arr.flat[0] += 1
        leaf["b"] = base64.b64encode(arr.tobytes()).decode("ascii")
        tampered += 1
    with open(cache, "w", encoding="utf-8") as f:
        for entry in lines:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
    refused, msg = False, ""
    try:
        # same runner (warm executables): a file-backed PrefixCache is
        # re-read from disk on every run_stream, so the tamper is seen
        runner.run_stream(pool, stretch=2, drain_chunk=8, shadow_every=1)
    except PrefixCacheError as exc:
        refused, msg = True, str(exc)
    checks = {
        "checkpoints_tampered": tampered > 0,
        "poison_refused_by_name": refused,
        "audit_named_the_fork": "fork shadow" in msg,
    }
    row = {"scenario": "prefix-poison-refused", "tampered": tampered,
           "error": msg[:160], "checks": checks,
           "ok": all(checks.values())}
    ok &= row["ok"]
    rows.append(row)
    log(f"prefix-poison-refused: {'ok' if row['ok'] else 'FAIL'} "
        f"tampered={tampered} refused={refused}"
        f"{'' if row['ok'] else ' checks=' + str(checks)}")
    return rows, ok


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seed", type=int, default=3)
    p.add_argument("--phases", type=int, default=16)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--fleet-only", action="store_true",
                   help="run only the serve-fleet scenarios (tier-1 slice)")
    p.add_argument("--prefix-only", action="store_true",
                   help="run only the prefix-fork scenarios (tier-1 slice)")
    args = p.parse_args()

    # keep off the real TPU chip when run standalone (same contract as the
    # test conftest); harmless under pytest where conftest already forced it
    if not os.environ.get("CLSIM_KEEP_PLATFORM"):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.fleet_only or args.prefix_only:
        t0 = time.time()
        rows, ok = (fleet_scenarios(args.seed) if args.fleet_only
                    else prefix_scenarios(args.seed))
        verdict = {"ok": ok, "scenarios": rows,
                   "elapsed_s": round(time.time() - t0, 1)}
        print(json.dumps(verdict))
        return 0 if ok else 1

    import jax

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.core.state import (
        ERR_FAULT_UNRECOVERED,
        ERR_SNAPSHOT_TIMEOUT,
        decode_error_bits,
    )
    from chandy_lamport_tpu.models.faults import JaxFaults
    from chandy_lamport_tpu.models.workloads import (
        ring_topology,
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.metrics import conservation_delta

    import numpy as np

    import dataclasses

    sf = scale_free(16, 2, seed=5, tokens=100)
    ring = ring_topology(8, tokens=100)
    cfg = SimConfig.for_workload(snapshots=2, max_recorded=128)
    # marker-plane scenarios run under the snapshot supervisor (ISSUE 4):
    # a generous retry budget for the recover-via-retry classes, a tight
    # one for the deliberate exhaustion
    sup_cfg = dataclasses.replace(cfg, snapshot_timeout=24,
                                  snapshot_retries=10)
    exhaust_cfg = dataclasses.replace(cfg, snapshot_timeout=10,
                                      snapshot_retries=2)
    s = args.seed

    # scenario := (name, topology, delay, phases, snapshot start phase,
    #              adversary, expected error bits)
    # the ring/FixedJaxDelay(1) crash scenarios pin snapshot completion
    # (~tick 17 for ring-8) on either side of the deterministic crash
    # window, so "recovers" vs "quarantines" is a scheduled outcome, not
    # a roll of the rates
    # one storm per DISTINCT trace (each rate set compiles fresh, and
    # compile dominates this battery's budget): the three message-plane
    # classes ride one combined scenario — per-class firing is still
    # asserted individually off fault_counts — and each crash outcome gets
    # its own scheduled program
    # the marker-plane rows (ISSUE 4): a drop storm against an ACTIVE
    # snapshot (initiated phase 1, drops all run) that must recover via
    # supervisor timeout+retry; a dup storm that must complete without the
    # duplicates corrupting the cut; and a total-loss run whose retry
    # budget is deliberately too small — the supervisor must fail LOUDLY
    # (ERR_SNAPSHOT_TIMEOUT, quarantined) rather than stall forever
    scenarios = [
        ("msg-faults", sf, make_fast_delay("hash", 11), args.phases, 1,
         JaxFaults(s, drop_rate=0.05, dup_rate=0.05, jitter_rate=0.05),
         ("drops", "dups", "jitters"), 0, cfg, None),
        ("crash-pause", sf, make_fast_delay("hash", 11), args.phases, 1,
         JaxFaults(s, crash_rate=0.5, crash_mode="pause",
                   crash_period=8, crash_len=2), ("crashes",), 0, cfg,
         None),
        ("crash-lossy-recovered", ring, FixedJaxDelay(1), 48, 1,
         JaxFaults(s, crash_rate=1.0, crash_mode="lossy",
                   crash_start=30, crash_len=2), ("crashes",), 0, cfg,
         None),
        ("crash-lossy-unrecovered", ring, FixedJaxDelay(1), 24, 1,
         JaxFaults(s, crash_rate=1.0, crash_mode="lossy",
                   crash_start=5, crash_len=2), ("crashes",),
         ERR_FAULT_UNRECOVERED, cfg, None),
        ("marker-drop-retry", ring, FixedJaxDelay(1), 24, 1,
         JaxFaults(s, marker_drop_rate=0.1), ("marker_drops",), 0,
         sup_cfg, "retry"),
        ("marker-dup-storm", ring, FixedJaxDelay(1), 24, 1,
         JaxFaults(s, marker_dup_rate=0.4), ("marker_dups",), 0,
         sup_cfg, "complete"),
        ("marker-drop-exhausted", ring, FixedJaxDelay(1), 16, 1,
         JaxFaults(s, marker_drop_rate=1.0), ("marker_drops",),
         ERR_SNAPSHOT_TIMEOUT, exhaust_cfg, "exhaust"),
    ]

    t0 = time.time()
    rows, ok = [], True
    for (name, spec, delay, phases, snap0, adversary, fired_classes,
         want_bits, scfg, sup_check) in scenarios:
        runner = BatchedRunner(spec, scfg, delay, batch=args.batch,
                               scheduler="exact", faults=adversary,
                               quarantine=True)
        prog = storm_program(
            runner.topo, phases=phases, amount=1,
            snapshot_phases=staggered_snapshots(runner.topo, 1, snap0, 2,
                                                max_phases=phases))
        final = jax.device_get(runner.run_storm(runner.init_batch(), prog))
        summary = BatchedRunner.summarize(final)
        lc = summary["snapshot_lifecycle"]
        expected = int(runner.topo.tokens0.sum()) * args.batch
        delta = int(conservation_delta(final, scfg, expected))
        errs = np.asarray(final.error)

        checks = {
            "fired": all(summary["fault_events"][c] > 0
                         for c in fired_classes),
            "books_balance": delta == 0,
            # no bit beyond the scenario's expected one, anywhere
            "no_unexpected_bits": not np.any(errs & ~want_bits),
            # and every expected injury actually quarantined: injured
            # lanes froze (did not reach the healthy lanes' max time)
            "injured_quarantined": (
                True if not want_bits else
                bool(np.all(errs & want_bits)
                     and np.all(np.asarray(final.time)[errs != 0]
                                < int(scfg.max_ticks)))),
        }
        if want_bits == 0:
            checks["recovered_clean"] = summary["error_lanes"] == 0
        if sup_check == "retry":
            # the drop storm stalled at least one attempt (timeout fired)
            # and every initiated snapshot still completed via retry
            checks["supervisor_retried"] = lc["retried"] > 0
            checks["all_completed"] = lc["completed"] == lc["initiated"]
        elif sup_check == "complete":
            checks["all_completed"] = lc["completed"] == lc["initiated"]
        elif sup_check == "exhaust":
            # total marker loss: every attempt burned its budget and
            # failed loudly — nothing completed, nothing wedged
            checks["supervisor_failed_loudly"] = (
                lc["failed"] > 0 and lc["completed"] == 0)
        row = {
            "scenario": name,
            "fault_events": summary["fault_events"],
            "fault_skew": summary["fault_skew"],
            "conservation_delta": delta,
            "errors_decoded": summary["errors_decoded"],
            "snapshot_lifecycle": lc,
            "quarantined_lanes": int((errs != 0).sum()),
            "checks": checks,
            "ok": all(checks.values()),
        }
        ok &= row["ok"]
        rows.append(row)
        log(f"{name}: {'ok' if row['ok'] else 'FAIL'} "
            f"events={summary['fault_events']} delta={delta} "
            f"errs={summary['errors_decoded']} "
            f"quarantined={row['quarantined_lanes']}"
            f"{'' if row['ok'] else ' checks=' + str(checks)}")

    # -- streaming with quarantine (ISSUE 6): under continuous lane
    #    scheduling (parallel/batch.run_stream) an injured job must be
    #    harvested into the results ring WITH its decoded bits intact, its
    #    slot recycled for the next queued job, and the healthy jobs must
    #    finish clean. Same scheduled lossy-crash adversary as
    #    crash-lossy-unrecovered, armed on every third JOB (per-job fault
    #    streams), so which rows carry ERR_FAULT_UNRECOVERED is
    #    deterministic in the queue, not in slot placement.
    from chandy_lamport_tpu.models.workloads import stream_jobs

    jcount = 10
    adversary = JaxFaults(s, crash_rate=1.0, crash_mode="lossy",
                          crash_start=5, crash_len=2)
    runner = BatchedRunner(ring, cfg, FixedJaxDelay(1), batch=args.batch,
                           scheduler="exact", faults=adversary,
                           quarantine=True)
    jobs = stream_jobs(ring, jcount, seed=s, base_phases=4, max_phases=12)
    armed = [j % 3 == 0 for j in range(jcount)]
    pool = runner.pack_jobs(jobs, fault_armed=armed)
    state, stream = runner.run_stream(pool, stretch=3, drain_chunk=16)
    res = runner.stream_results(stream)
    sc = runner.summarize_stream(stream)
    errored = [r for r in res if r["error"]]
    # every harvested slot is reset to the fresh template, so the FINAL
    # state must hold exactly the template tokens again — the streaming
    # books balance even though lossy crashes moved tokens mid-queue
    # (each job's own skew was harvested into its results-ring row)
    delta = int(conservation_delta(
        jax.device_get(state), cfg,
        int(runner.topo.tokens0.sum()) * args.batch))
    checks = {
        "books_balance": delta == 0,
        # the queue drains even with casualties: every job harvested
        "queue_drained": sc["jobs_done"] == jcount and len(res) == jcount,
        # quarantined slots were actually recycled for later jobs
        "slots_recycled": sc["refills"] > 0,
        "some_quarantined": len(errored) > 0,
        "errors_preserved": all(r["error"] & ERR_FAULT_UNRECOVERED
                                for r in errored),
        "only_armed_injured": all(armed[r["job"]] for r in errored),
        "healthy_jobs_clean": all(r["error"] == 0 for r in res
                                  if not armed[r["job"]]),
    }
    row = {"scenario": "stream-quarantine-refill", "stream": sc,
           "conservation_delta": delta, "jobs_errored": len(errored),
           "errors_decoded": sorted({d for r in errored
                                     for d in r["errors_decoded"]}),
           "checks": checks, "ok": all(checks.values())}
    ok &= row["ok"]
    rows.append(row)
    log(f"stream-quarantine-refill: {'ok' if row['ok'] else 'FAIL'} "
        f"jobs_done={sc['jobs_done']} refills={sc['refills']} "
        f"errored={len(errored)}"
        f"{'' if row['ok'] else ' checks=' + str(checks)}")

    # -- flight recorder under faults (ISSUE 7): the decoded device
    #    timeline must SHOW the supervisor's recovery, not just count it.
    #    Same marker-drop storm as marker-drop-retry but with the trace
    #    armed: some lane's event stream must carry supervisor-abort
    #    followed by supervisor-retry followed by a fresh marker-send —
    #    the re-initiation, readable straight off the ring.
    from chandy_lamport_tpu.utils.tracing import (
        EV_MSEND,
        EV_SUP_ABORT,
        EV_SUP_RETRY,
        JaxTrace,
        decode_trace,
        trace_counts,
    )

    adversary = JaxFaults(s, marker_drop_rate=0.1)
    runner = BatchedRunner(ring, sup_cfg, FixedJaxDelay(1), batch=args.batch,
                           scheduler="exact", faults=adversary,
                           quarantine=True, trace=JaxTrace())
    prog = storm_program(
        runner.topo, phases=24, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, 1, 1, 2,
                                            max_phases=24))
    final = jax.device_get(runner.run_storm(runner.init_batch(), prog))
    summary = BatchedRunner.summarize(final)
    lc = summary["snapshot_lifecycle"]
    delta = int(conservation_delta(
        final, sup_cfg, int(runner.topo.tokens0.sum()) * args.batch))
    rec, dropped = trace_counts(final)
    seq_ok = False
    for lane in range(args.batch):
        evs = decode_trace(final, lane=lane)
        t_abort = next((e.tick for e in evs if e.kind == EV_SUP_ABORT), None)
        if t_abort is None:
            continue
        t_retry = next((e.tick for e in evs
                        if e.kind == EV_SUP_RETRY and e.tick >= t_abort),
                       None)
        if t_retry is not None and any(
                e.kind == EV_MSEND and e.tick > t_retry for e in evs):
            seq_ok = True
            break
    checks = {
        "supervisor_retried": lc["retried"] > 0,
        "all_completed": lc["completed"] == lc["initiated"],
        "recovered_clean": summary["error_lanes"] == 0,
        "books_balance": delta == 0,
        "events_recorded": rec > 0 and dropped == 0,
        "abort_retry_reinit_visible": seq_ok,
    }
    row = {"scenario": "trace-under-faults",
           "trace_events": rec, "trace_dropped": dropped,
           "conservation_delta": delta,
           "snapshot_lifecycle": lc, "checks": checks,
           "ok": all(checks.values())}
    ok &= row["ok"]
    rows.append(row)
    log(f"trace-under-faults: {'ok' if row['ok'] else 'FAIL'} "
        f"events={rec} retried={lc['retried']}"
        f"{'' if row['ok'] else ' checks=' + str(checks)}")

    frows, fok = fleet_scenarios(args.seed)
    rows += frows
    ok &= fok

    prows, pok = prefix_scenarios(args.seed)
    rows += prows
    ok &= pok

    verdict = {"ok": ok, "scenarios": rows,
               "elapsed_s": round(time.time() - t0, 1)}
    print(json.dumps(verdict))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
