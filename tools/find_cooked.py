#!/usr/bin/env python3
"""Select the correct regenerated rngCooked table using the golden fixtures.

Search space (see tools/gen_cooked.py): 2 bootstrap-shift variants x 3 output
orderings for the table, crossed with 2 possible Seed() packing shifts. A
candidate is accepted only if the parity backend reproduces ALL 21 golden
snapshots across all 7 reference test cases. On success, vendors the table to
chandy_lamport_tpu/data/gorand_cooked.npy and prints the winning combo.
"""

import glob
import itertools
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from chandy_lamport_tpu.config import REFERENCE_TEST_SEED
from chandy_lamport_tpu.core.parity import ParitySim, run_events
from chandy_lamport_tpu.models.delay import GoExactDelay
from chandy_lamport_tpu.utils.compare import (
    assert_snapshots_equal,
    check_tokens,
    sort_snapshots,
)
from chandy_lamport_tpu.utils.fixtures import (
    read_events_file,
    read_snapshot_file,
    read_topology_file,
)

DATA = os.path.join(os.path.dirname(__file__), "..", "chandy_lamport_tpu", "data")
TESTS = [
    ("2nodes.top", "2nodes-simple.events", ["2nodes-simple.snap"]),
    ("2nodes.top", "2nodes-message.events", ["2nodes-message.snap"]),
    ("3nodes.top", "3nodes-simple.events", ["3nodes-simple.snap"]),
    ("3nodes.top", "3nodes-bidirectional-messages.events",
     ["3nodes-bidirectional-messages.snap"]),
    ("8nodes.top", "8nodes-sequential-snapshots.events",
     [f"8nodes-sequential-snapshots{i}.snap" for i in range(2)]),
    ("8nodes.top", "8nodes-concurrent-snapshots.events",
     [f"8nodes-concurrent-snapshots{i}.snap" for i in range(5)]),
    ("10nodes.top", "10nodes.events", [f"10nodes{i}.snap" for i in range(10)]),
]


def try_combo(cooked, seed_shifts, tests):
    for top, events, snaps in tests:
        td = os.path.join(DATA, "test_data")
        topo = read_topology_file(os.path.join(td, top))
        evs = read_events_file(os.path.join(td, events))
        dm = GoExactDelay(REFERENCE_TEST_SEED + 1, cooked=cooked, seed_shifts=seed_shifts)
        sim = ParitySim(dm)
        for nid, tok in topo.nodes:
            sim.add_node(nid, tok)
        for s, d in topo.links:
            sim.add_link(s, d)
        actual = run_events(sim, evs)
        expected = [read_snapshot_file(os.path.join(td, f)) for f in snaps]
        if len(actual) != len(expected):
            return f"{events}: snapshot count {len(actual)} != {len(expected)}"
        check_tokens(sim.node_tokens(), actual)
        for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
            assert_snapshots_equal(e, a)
    return None


def main():
    candidates = sorted(glob.glob(os.path.join(DATA, "cooked_candidates", "*.npy")))
    assert candidates, "run tools/gen_cooked.py first"
    winners = []
    # Discriminating subset first (3nodes draws many times), full run for survivors.
    quick = [TESTS[2]]
    for path, seed_shifts in itertools.product(candidates, [(40, 20), (20, 10)]):
        cooked = np.load(path)
        try:
            err = try_combo(cooked, seed_shifts, quick)
        except Exception as e:  # mismatch exceptions count as failures
            err = str(e)
        tag = f"{os.path.basename(path)} seed_shifts={seed_shifts}"
        if err:
            print(f"FAIL  {tag}: {err[:110]}")
            continue
        try:
            err = try_combo(cooked, seed_shifts, TESTS)
        except Exception as e:
            err = str(e)
        if err:
            print(f"PARTIAL {tag}: passed 3nodes but: {err[:110]}")
            continue
        print(f"PASS  {tag}: all 7 tests / 21 goldens")
        winners.append((path, seed_shifts, cooked))
    if len(winners) == 1:
        path, seed_shifts, cooked = winners[0]
        out = os.path.join(DATA, "gorand_cooked.npy")
        np.save(out, cooked)
        print(f"\nvendored {os.path.basename(path)} (seed_shifts={seed_shifts}) -> {out}")
        if seed_shifts != (40, 20):
            print("WARNING: update GoRand default seed_shifts to", seed_shifts)
    elif not winners:
        print("\nNO candidate passed — widen the search (discard count? orderings?)")
        sys.exit(1)
    else:
        print(f"\nAMBIGUOUS: {len(winners)} winners — need a tie-breaker")
        sys.exit(2)


if __name__ == "__main__":
    main()
