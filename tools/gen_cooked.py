#!/usr/bin/env python3
"""Regenerate Go's ``math/rand`` legacy ``rngCooked`` seeding table from scratch.

Go's legacy PRNG (``rngSource``) is a 607-lag / 273-tap additive lagged-Fibonacci
generator over Z/2^64.  Its ``Seed`` method XORs a Schrage-LCG seed chain with a
precomputed 607-entry table ``rngCooked`` — the generator state obtained by
seeding a bootstrap state with 1 and discarding 7.8e12 outputs (per Go's
``gen_cooked.go``).  That table cannot be fetched here (zero egress, no Go
toolchain on the machine — verified), so we regenerate it.

The recurrence ``vec[feed] += vec[tap]`` is *linear* over Z/2^64, so instead of
7.8e12 scalar steps (~hours) we exponentiate the 607-step block matrix B
(each block updates every lane exactly once and returns tap/feed to their
starting positions):  state_after = B^q @ state0, then r = N mod 607 residual
scalar steps.  B^q needs ~34 squarings of a 607x607 matrix over Z/2^64, done
exactly with float64 BLAS via 16-bit limb decomposition (products < 2^32,
row-sums < 2^32 * 607 < 2^53, so float64 matmul is exact).

Because two details of the upstream bootstrap are not reliably derivable from
memory, we emit *candidate* tables over a small search space and let the 21
golden snapshot fixtures (the ground-truth oracle) pick the right one:
  - bootstrap srand() packing shifts: (20,10,0) or (40,20,0)
  - output ordering: vec[(tap+i)%607], vec[i], or vec[(feed+i)%607]

Usage: python tools/gen_cooked.py [--selftest] [--out DIR]
Writes candidates to DIR (default chandy_lamport_tpu/data/cooked_candidates/).
"""

import argparse
import os

import numpy as np

LEN = 607
TAP = 273
FEED0 = LEN - TAP  # 334
MASK64 = (1 << 64) - 1
# Schrage LCG constants (Go math/rand rng.go / gen_cooked.go)
A, M, Q, R = 48271, (1 << 31) - 1, 44488, 3399
DISCARD = 7_800_000_000_000  # gen_cooked.go discard count


def seedrand(x: int) -> int:
    """One step of the Schrage-split Lehmer LCG: x = A*x mod M without overflow."""
    hi, lo = divmod(x, Q)
    x = A * lo - R * hi
    if x < 0:
        x += M
    return x


def bootstrap_state(seed: int, shifts) -> np.ndarray:
    """srand(): fill the 607-lane state from the LCG chain (gen_cooked.go srand)."""
    s1, s2 = shifts
    seed %= M
    if seed < 0:
        seed += M
    if seed == 0:
        seed = 89482311
    x = seed
    vec = np.zeros(LEN, dtype=np.uint64)
    for i in range(-20, LEN):
        x = seedrand(x)
        if i >= 0:
            u = (x << s1) & MASK64
            x = seedrand(x)
            u ^= (x << s2) & MASK64
            x = seedrand(x)
            u ^= x
            vec[i] = u
    return vec


def direct_steps(vec: np.ndarray, n: int, tap: int = 0, feed: int = FEED0):
    """n scalar vrand() steps: tap--, feed-- (mod LEN), vec[feed] += vec[tap]."""
    v = vec.copy()
    for _ in range(n):
        tap = (tap - 1) % LEN
        feed = (feed - 1) % LEN
        v[feed] = v[feed] + v[tap]  # uint64 wraparound
    return v, tap, feed


def block_matrix() -> np.ndarray:
    """B such that 607 vrand steps == B @ v (over Z/2^64).

    Apply the 607 elementary row operations to the identity matrix.
    """
    B = np.eye(LEN, dtype=np.uint64)
    tap, feed = 0, FEED0
    for _ in range(LEN):
        tap = (tap - 1) % LEN
        feed = (feed - 1) % LEN
        B[feed, :] += B[tap, :]
    assert tap == 0 and feed == FEED0
    return B


def matmul_u64(X: np.ndarray, Y: np.ndarray) -> np.ndarray:
    """Exact (X @ Y) mod 2^64 using 16-bit limbs + float64 BLAS."""
    xl = [((X >> np.uint64(16 * k)) & np.uint64(0xFFFF)).astype(np.float64) for k in range(4)]
    yl = [((Y >> np.uint64(16 * k)) & np.uint64(0xFFFF)).astype(np.float64) for k in range(4)]
    out = np.zeros(X.shape[:1] + Y.shape[1:], dtype=np.uint64)
    for i in range(4):
        for j in range(4 - i):
            p = (xl[i] @ yl[j]).astype(np.uint64)  # exact: < 2^32 * 607 < 2^53
            out += p << np.uint64(16 * (i + j))  # wraps mod 2^64
    return out


def matvec_u64(Mx: np.ndarray, v: np.ndarray) -> np.ndarray:
    return (Mx * v[None, :]).sum(axis=1, dtype=np.uint64)


def jump(vec: np.ndarray, n: int):
    """State after n vrand steps from (tap=0, feed=FEED0), via matrix exponentiation."""
    q, r = divmod(n, LEN)
    v = vec.copy()
    P = block_matrix()
    while q:
        if q & 1:
            v = matvec_u64(P, v)
        q >>= 1
        if q:
            P = matmul_u64(P, P)
    return direct_steps(v, r)


def selftest():
    v0 = bootstrap_state(1, (20, 10))
    for n in (0, 1, 606, 607, 608, 12345):
        a, ta, fa = jump(v0, n)
        b, tb, fb = direct_steps(v0, n)
        assert (a == b).all() and ta == tb and fa == fb, f"jump mismatch at n={n}"
    # matmul_u64 sanity vs python ints on random small matrices
    rng = np.random.default_rng(0)
    X = rng.integers(0, 1 << 64, size=(13, 13), dtype=np.uint64)
    Y = rng.integers(0, 1 << 64, size=(13, 13), dtype=np.uint64)
    Z = matmul_u64(X, Y)
    for i in range(13):
        for j in range(13):
            want = sum(int(X[i, k]) * int(Y[k, j]) for k in range(13)) & MASK64
            assert int(Z[i, j]) == want
    print("selftest OK")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--out", default=os.path.join(os.path.dirname(__file__), "..",
                                                  "chandy_lamport_tpu", "data",
                                                  "cooked_candidates"))
    args = ap.parse_args()
    if args.selftest:
        selftest()
        return
    os.makedirs(args.out, exist_ok=True)
    for shifts in ((20, 10), (40, 20)):
        v0 = bootstrap_state(1, shifts)
        vec, tap, feed = jump(v0, DISCARD)
        for name, order in (
            ("tap", (np.arange(LEN) + tap) % LEN),
            ("raw", np.arange(LEN)),
            ("feed", (np.arange(LEN) + feed) % LEN),
        ):
            table = vec[order]
            path = os.path.join(args.out, f"cooked_s{shifts[0]}_{shifts[1]}_{name}.npy")
            np.save(path, table)
            print(path, "first:", table[0], "tap:", tap, "feed:", feed)


if __name__ == "__main__":
    main()
