#!/usr/bin/env python
"""Run the BASELINE.md config ladder and emit one JSON line per config.

Each config is a bench invocation (same engine, same JSON contract, same
platform-fallback ladder), so every row carries platform/device_kind and can
never silently be a CPU number pretending to be TPU. Results append to
``BASELINE_MEASURED.jsonl`` at the repo root and print to stdout.

Configs (BASELINE.json):
  2: 10-node ring, 1 initiator, 128 instances            — first batched run
  3: 256-node Erdős–Rényi(avg 3), 4k instances           — single-chip scale
  4: 1k-node scale-free, 8 initiators/instance           — the metric config
  5: largest single-chip approximation of "8k nodes x 1M instances":
     8k-node scale-free at the max batch that fits one chip's HBM
     (the literal config-5 needs ~18 MB/instance x 1M = 17.8 PB — see
     BASELINE.md for the footprint math)

Usage: python tools/ladder.py [--quick] [--scheduler sync|exact|both]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def bench(name: str, extra: list, timeout: float) -> dict:
    cmd = [sys.executable, os.path.join(ROOT, "bench.py"),
           "--timeout", str(timeout)] + extra
    print(f"--- {name}: {' '.join(cmd)}", file=sys.stderr, flush=True)
    proc = subprocess.run(cmd, stdout=subprocess.PIPE, cwd=ROOT)
    lines = proc.stdout.decode().strip().splitlines()
    if not lines:  # bench guarantees a line unless killed from outside
        return {"config": name, "error": "no output", "rc": proc.returncode}
    row = json.loads(lines[-1])
    # honest labels (round-2 VERDICT): a run the bench's platform-fallback
    # ladder clamped to a smaller shape must not carry the full-shape config
    # name — compare what was asked against what actually ran
    asked = {extra[i].lstrip("-"): extra[i + 1]
             for i in range(0, len(extra) - 1, 2) if extra[i].startswith("--")}
    clamped = any(
        key in row and str(row[key]) != asked[key]
        for key in ("nodes", "batch", "phases", "repeats") if key in asked)
    row["config"] = name + ("_CLAMPED" if clamped else "")
    return row


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="shrink batches ~8x for a fast smoke pass")
    p.add_argument("--scheduler", choices=["sync", "exact", "both"],
                   default="sync")
    p.add_argument("--exact-impl", choices=["cascade", "wave", "both"],
                   default="cascade",
                   help="bit-exact formulation(s) for the ladder's exact "
                        "rows (forwarded to bench --exact-impl); 'both' "
                        "runs a cascade/wave A/B pair per config — the "
                        "wave is the competitive exact number at marker-"
                        "heavy shapes (ops/tick._wave_tick)")
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--delay", choices=["uniform", "hash"], default=None,
                   help="forwarded to bench --delay")
    p.add_argument("--out", default=os.path.join(ROOT, "BASELINE_MEASURED.jsonl"))
    args = p.parse_args()

    q = 8 if args.quick else 1
    ladder = [
        ("config2_ring10", ["--graph", "ring", "--nodes", "10",
                            "--batch", str(max(128 // q, 16)),
                            "--phases", "32", "--snapshots", "1"]),
        ("config3_er256", ["--graph", "er", "--nodes", "256",
                           "--batch", str(max(4096 // q, 64)),
                           "--phases", "32", "--snapshots", "4"]),
        ("config4_sf1k", ["--graph", "sf", "--nodes", "1024",
                          "--batch", str(max(2048 // q, 32)),
                          "--phases", "32", "--snapshots", "8"]),
        ("config5_sf8k_maxbatch", ["--graph", "sf", "--nodes", "8192",
                                   "--batch", str(max(512 // q, 8)),
                                   "--phases", "16", "--snapshots", "8"]),
    ]
    schedulers = (["sync", "exact"] if args.scheduler == "both"
                  else [args.scheduler])
    impls = (["cascade", "wave"] if args.exact_impl == "both"
             else [args.exact_impl])
    n = 0
    for name, extra in ladder:
        for sched in schedulers:
            # one rung per exact formulation (sync ignores the impl axis);
            # row names keep the historical `{config}_exact` spelling for
            # the cascade so banked-row resume logic elsewhere still hits
            for impl in (impls if sched == "exact" else ["cascade"]):
                run = list(extra)
                # (round 4) exact runs at the full sync batch: the cascade
                # tick (ops/tick._cascade_tick) removed the N-step per-tick
                # scan whose live carries cost ~8x the sync path's HBM and
                # faulted the device at N=8192 — the old /8 clamp is gone
                if args.delay:
                    run += ["--delay", args.delay]
                run += ["--scheduler", sched]
                label = f"{name}_{sched}"
                if sched == "exact":
                    # the wave needs a position-addressable sampler; the
                    # bench default (hash) is one, but pin it so a future
                    # --delay uniform pass can't silently break the rung
                    run += ["--exact-impl", impl]
                    if not args.delay:
                        run += ["--delay", "hash"]
                    if impl != "cascade":
                        label += f"_{impl}"
                row = bench(label, run, args.timeout)
                print(json.dumps(row), flush=True)
                # append immediately so a later config's crash loses nothing
                with open(args.out, "a") as f:
                    f.write(json.dumps(row) + "\n")
                n += 1
    print(f"appended {n} rows to {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
