#!/usr/bin/env python
"""Find the largest instance batch that fits the current device.

Doubles the batch until allocation/compilation fails with an out-of-memory
error, then bisects the boundary. Each probe runs a short storm (2 phases +
drain) so the measurement includes XLA's real working set, not just the
state arrays. Prints one JSON line: the max batch, the footprint-model
prediction, and their ratio (the empirical working-set factor).

The 1M-instance north-star configuration is `--graph ring --nodes 10
--max-snapshots 2` (BASELINE.md: ~7 kB/instance). Use CLSIM_PLATFORM=cpu
off-TPU (RAM-bound there, so only the harness logic is meaningful).

Usage: python tools/maxbatch.py [--nodes N] [--graph sf|ring|er] [--start B]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# the BASELINE.md ladder configs as one-flag presets ("max instances per
# ladder config", VERDICT r3 #6); start batches sized so the doubling walk
# reaches the boundary in a few probes
PRESETS = {
    "northstar": dict(graph="ring", nodes=10, max_snapshots=2, start=1 << 18),
    "config2": dict(graph="ring", nodes=10, max_snapshots=8, start=1 << 16),
    "config3": dict(graph="er", nodes=256, max_snapshots=8, start=1 << 12),
    "config4": dict(graph="sf", nodes=1024, max_snapshots=8, start=1 << 10),
    "config5": dict(graph="sf", nodes=8192, max_snapshots=8, start=1 << 7),
}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", choices=sorted(PRESETS), default=None,
                   help="a BASELINE.md ladder config (fills "
                        "--graph/--nodes/--max-snapshots/--start; "
                        "explicit flags win)")
    # preset-controlled flags parse as None so an EXPLICIT value equal to
    # the fallback is distinguishable from "not passed" (the old
    # value == parser-default test silently let the preset override
    # explicit flags); fallbacks are filled after the merge below
    p.add_argument("--nodes", type=int, default=None)
    p.add_argument("--graph", choices=["sf", "ring", "er"], default=None)
    p.add_argument("--attach", type=int, default=2)
    p.add_argument("--start", type=int, default=None)
    p.add_argument("--limit", type=int, default=1 << 22)
    p.add_argument("--max-snapshots", type=int, default=None)
    p.add_argument("--record-dtype", choices=["int32", "int16"],
                   default="int32")
    args = p.parse_args()
    preset = PRESETS[args.preset] if args.preset else {}
    fallbacks = dict(nodes=1024, graph="sf", start=256, max_snapshots=8)
    # a preset key outside the None-defaulted merge set would be silently
    # dropped — fail loudly instead if one is ever added
    assert set(preset) <= set(fallbacks), sorted(set(preset) - set(fallbacks))
    for k, fallback in fallbacks.items():
        if getattr(args, k) is None:
            setattr(args, k, preset.get(k, fallback))

    platform = os.environ.get("CLSIM_PLATFORM")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import numpy as np

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        ring_topology,
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import UniformJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.metrics import instance_footprint_bytes

    dev = jax.devices()[0]
    cfg = SimConfig(queue_capacity=16, max_snapshots=args.max_snapshots,
                    max_recorded=16, record_dtype=args.record_dtype)
    if args.graph == "ring":
        spec = ring_topology(args.nodes, tokens=20)
    elif args.graph == "er":
        spec = erdos_renyi(args.nodes, 3.0, seed=3, tokens=20)
    else:
        spec = scale_free(args.nodes, args.attach, seed=3, tokens=20)

    probed_ok = [False]  # at least one successful probe so far

    def probe(batch: int) -> bool:
        """True iff a short storm at this batch completes on device."""
        try:
            runner = BatchedRunner(spec, cfg, UniformJaxDelay(seed=7),
                                   batch=batch, scheduler="sync")
            prog = storm_program(
                runner.topo, phases=2, amount=1,
                snapshot_phases=staggered_snapshots(runner.topo, 1))
            t0 = time.perf_counter()
            # device-side init: a 1M-instance host state would take minutes
            # to build and ship through the remote tunnel
            final = runner.run_storm(runner.init_batch_device(), prog)
            jax.block_until_ready(final)
            ok = int(np.asarray(jax.device_get(final.error)).sum()) == 0
            log(f"batch {batch}: OK ({time.perf_counter() - t0:.1f}s, "
                f"errors={'no' if ok else 'YES'})")
            probed_ok[0] = probed_ok[0] or ok
            return ok
        except Exception as exc:
            msg = str(exc)
            # the remote-compile tunnel wraps OOM as INTERNAL with the XLA
            # message text — always "does not fit". A near-capacity probe
            # can also fault the device outright (UNAVAILABLE), but that
            # status equally means preemption or a tunnel restart, so it
            # only counts as does-not-fit once a smaller batch has
            # succeeded this run; before that it is a real failure.
            oom = any(pat in msg for pat in (
                "RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Ran out of memory", "Exceeded hbm capacity",
            )) or isinstance(exc, MemoryError)
            oom = oom or (probed_ok[0] and "UNAVAILABLE" in msg)
            log(f"batch {batch}: {'does-not-fit' if oom else 'FAIL'} "
                f"({type(exc).__name__}: {msg[:160]})")
            if not oom:
                raise
            return False

    hi = args.start
    lo = 0
    while hi <= args.limit and probe(hi):
        lo, hi = hi, hi * 2
    hi = min(hi, args.limit + 1)  # --limit caps the search, not just doubling
    if lo == 0:
        log("start batch already OOM; lower --start")
        lo, hi = 1, args.start
    while hi - lo > max(lo // 16, 1):  # ~6% resolution
        mid = (lo + hi) // 2
        if probe(mid):
            lo = mid
        else:
            hi = mid
    e = {"ring": args.nodes, "er": int(args.nodes * 3),
         "sf": args.nodes * (1 + args.attach)}[args.graph]
    per = instance_footprint_bytes(args.nodes, e, cfg)
    stats = {}
    try:
        m = dev.memory_stats() or {}
        stats = {"hbm_limit_bytes": int(m.get("bytes_limit", 0))}
    except Exception:
        pass
    result = {
        "metric": "max_batch",
        "value": lo,
        "unit": "instances",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "preset": args.preset,
        "graph": args.graph,
        "nodes": args.nodes,
        "max_snapshots": args.max_snapshots,
        "record_dtype": args.record_dtype,
        "footprint_bytes_per_instance": per,
        "resident_gb_at_max": round(per * lo / 1e9, 2),
        # concurrent snapshot slots resident at the max batch — the literal
        # second axis of the north-star metric
        "max_concurrent_snapshot_slots": lo * args.max_snapshots,
    }
    result.update(stats)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
