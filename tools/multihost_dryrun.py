#!/usr/bin/env python
"""Two-process loopback dryrun of the multi-host runtime (no real DCN).

Round-2 VERDICT: ``multihost.initialize`` had zero execution coverage —
single-process tests only ever exercised the no-op path. This tool brings up
JAX's multi-controller runtime for real: two local processes, a loopback
coordinator, two virtual CPU devices per process, and then

  1. asserts each process sees process_count == 2 and 4 global devices;
  2. builds ``hybrid_mesh(graph=2)`` — data axis spanning the processes
     (the DCN analogue), graph axis inside each process (the ICI analogue);
  3. runs a jitted global reduction over an array sharded on the data axis
     (a genuine cross-process collective through the distributed runtime);
  4. runs a small batched storm per process and all-reduces the summary
     counters across processes — the exact aggregation path a multi-host
     1M-instance run uses (parallel/multihost.py module docstring);
  5. runs the graph-sharded runner's sparse halo exchange across the
     fabric twice — graph-only (the graph axis spanning both processes,
     boundary ppermutes through the coordinator-connected transport,
     sparse-vs-dense finals compared by a jitted replicated reduction)
     and dp x graph on the hybrid mesh — and reports the per-tick
     comm-bytes model in the worker JSON.

Usage: python tools/multihost_dryrun.py            # parent: spawns 2 workers
       (exit 0 and a one-line JSON verdict on stdout)
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child() -> int:
    sys.path.insert(0, ROOT)
    import jax

    # the env var alone is not enough on this image: the TPU plugin sets
    # jax_platforms programmatically at import time (same workaround as
    # bench.py/conftest.py) — force CPU before the backend initializes
    jax.config.update("jax_platforms", "cpu")
    from functools import partial

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from chandy_lamport_tpu.parallel import multihost

    assert multihost.initialize(), "expected distributed init, got no-op"
    info = multihost.process_info()
    assert info["process_count"] == 2, info
    assert info["global_devices"] == 4, info
    assert info["local_devices"] == 2, info

    mesh = multihost.hybrid_mesh(graph=2)
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 2, "graph": 2}, mesh

    # cross-process collective: each process contributes its rank+1 on its
    # slice of a data-sharded array; the jitted global sum must see both
    rank = info["process_index"]
    local = np.full((1, 4), rank + 1, np.int32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data", None)), local, (2, 4))
    total = int(jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr))
    assert total == 4 * (1 + 2), total

    # the DP aggregation path: independent storm per process, counters
    # all-reduced over the fabric (multihost_utils wraps the same collective
    # a sharded summarize() lowers to)
    from jax.experimental import multihost_utils

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import (
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import UniformJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    runner = BatchedRunner(scale_free(8, 2, seed=1, tokens=20),
                           SimConfig.for_workload(snapshots=2),
                           UniformJaxDelay(seed=100 + rank), batch=2,
                           scheduler="sync")
    prog = storm_program(runner.topo, phases=4, amount=1,
                         snapshot_phases=staggered_snapshots(
                             runner.topo, 2, 1, 1, max_phases=4))
    final = runner.run_storm(runner.init_batch_device(), prog)
    summary = BatchedRunner.summarize(final)
    assert summary["error_bits"] == 0, summary
    done = np.array([summary["snapshots_completed"]], np.int32)
    global_done = int(multihost_utils.process_allgather(done).sum())
    assert global_done == 2 * summary["snapshots_completed"], global_done

    # sparse halo exchange over the real multi-controller runtime.
    # (a) graph-only: one giant instance, the graph axis spanning BOTH
    # processes, so the boundary ppermutes cross the DCN analogue; sparse
    # (with a megatick-2 drain) and dense finals must agree leaf-for-leaf,
    # checked by a jitted reduction to a replicated scalar (per-process
    # device_get of a cross-process-sharded tree is not addressable)
    from chandy_lamport_tpu.models.workloads import erdos_renyi
    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner

    gmesh = Mesh(np.array(jax.devices()), ("graph",))
    gspec = erdos_renyi(16, 2.5, seed=13, tokens=40)
    gcfg = SimConfig(max_snapshots=4)
    gfinals, comm_model = {}, None
    for engine in ("sparse", "dense"):
        gs = GraphShardedRunner(gspec, gcfg, gmesh, seed=3,
                                comm_engine=engine,
                                megatick=2 if engine == "sparse" else 1)
        gprog = storm_program(gs.topo, phases=4, amount=1,
                              snapshot_phases=staggered_snapshots(gs.topo, 2))
        gfinals[engine] = gs.run_storm(gs.init_state(),
                                       np.asarray(gprog.amounts),
                                       np.asarray(gprog.snap))
        if engine == "sparse":
            comm_model = gs.comm_model()

    grep = NamedSharding(gmesh, P())

    @partial(jax.jit, out_shardings=grep)
    def _agree(a, b):
        eq = jnp.bool_(True)
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            eq = eq & jnp.all(x == y)
        return eq

    engines_agree = bool(_agree(gfinals["sparse"], gfinals["dense"]))
    assert engines_agree, "sparse/dense diverge on the cross-process mesh"
    gmet = jax.jit(lambda f: jnp.stack([f.error, f.completed[0]]),
                   out_shardings=grep)(gfinals["sparse"])
    gerr, gcomp = (int(x) for x in np.asarray(gmet))
    assert gerr == 0, "graph-only sparse dry run error"
    assert gcomp == 16, gcomp

    # (b) dp x graph on the hybrid mesh: lanes shard over "data" (across
    # the processes), each lane's halo exchange rides "graph" (inside one)
    cspec = erdos_renyi(8, 2.5, seed=21, tokens=40)
    cgs = GraphShardedRunner(cspec, SimConfig(max_snapshots=4), mesh,
                             seed=5, comm_engine="sparse")
    cprog = storm_program(cgs.topo, phases=4, amount=1,
                          snapshot_phases=staggered_snapshots(cgs.topo, 2))
    batch = 2 * mesh.shape["data"]
    cfinal = cgs.run_storm_batched(cgs.init_batch(batch),
                                   np.asarray(cprog.amounts),
                                   np.asarray(cprog.snap))
    cmet = jax.jit(lambda f: jnp.stack([jnp.sum(f.error),
                                        jnp.sum(f.completed[:, 0])]),
                   out_shardings=NamedSharding(mesh, P()))(cfinal)
    cerr, ccomp = (int(x) for x in np.asarray(cmet))
    assert cerr == 0, "dp x graph sparse dry run error"
    assert ccomp == batch * cgs.topo.n, (ccomp, batch, cgs.topo.n)

    print(json.dumps({"rank": rank,
                      "global_snapshots_completed": global_done,
                      "graph_engines_agree": engines_agree,
                      "dp_graph_lanes": batch,
                      "comm_bytes_model": comm_model}),
          flush=True)
    return 0


def main() -> int:
    if "--child" in sys.argv:
        return _child()

    with socket.socket() as s:  # free loopback port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(rank),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PYTHONPATH": ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    ok = True
    outputs = []
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            ok = False
        if p.returncode != 0:
            ok = False
            sys.stderr.write(f"--- rank {rank} rc={p.returncode}\n"
                             + err.decode(errors="replace")[-2000:] + "\n")
        outputs.append(out.decode(errors="replace").strip())
    print(json.dumps({"ok": ok, "workers": outputs}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
