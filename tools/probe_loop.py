#!/usr/bin/env python
"""Opportunistic TPU-tunnel watchdog (VERDICT r4 next-round item #1).

The axon device tunnel has been down for two whole build rounds; the
measured TPU rows (BASELINE.md) all predate round 4. This loop turns the
single end-of-round bench lottery ticket into continuous sampling: every
``--interval`` seconds it fires the bench's own liveness probe
(``python -m chandy_lamport_tpu.bench --probe`` — jax.devices() + a tiny
jit in a subprocess) under a short timeout, appends one JSON line per
attempt to ``tools/probe_log.jsonl``, and the moment a probe answers
``platform == "tpu"`` it runs the queued measurement plan
(``tools/r5_measure.py``) exactly once, then keeps probing (a later
window can still refresh rows with ``--rearm``).

Designed to run unattended in tmux for the whole build round:

    python tools/probe_loop.py --interval 900

What it replaces at measurement time: the reference hot loop the rows
time, /root/reference/chandy_lamport/sim.go:71-95.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "tools", "probe_log.jsonl")


def now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(timespec="seconds")


def append(row: dict) -> None:
    row = {"ts": now(), **row}
    with open(LOG, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row), flush=True)


def probe(timeout: float) -> dict:
    cmd = [sys.executable, "-m", "chandy_lamport_tpu.bench", "--probe"]
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, cwd=ROOT,
                              timeout=timeout)
        dt = time.monotonic() - t0
        lines = proc.stdout.decode().strip().splitlines()
        if lines:
            try:
                row = json.loads(lines[-1])
                return {"result": "ok", "elapsed_s": round(dt, 1), **row}
            except json.JSONDecodeError:
                pass
        return {"result": "fail", "rc": proc.returncode,
                "elapsed_s": round(dt, 1)}
    except subprocess.TimeoutExpired:
        return {"result": "hang", "elapsed_s": round(time.monotonic() - t0, 1)}


def measure(timeout: float, only: str) -> int:
    cmd = [sys.executable, os.path.join(ROOT, "tools", "r5_measure.py")]
    if only:
        cmd += ["--only", only]
    append({"event": "measure_start", "cmd": " ".join(cmd)})
    rc = subprocess.call(cmd, cwd=ROOT, timeout=timeout)
    append({"event": "measure_done", "rc": rc})
    return rc


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--interval", type=float, default=900.0,
                   help="seconds between probe attempts (default 15 min)")
    p.add_argument("--probe-timeout", type=float, default=120.0)
    p.add_argument("--measure-timeout", type=float, default=4 * 3600.0,
                   help="budget for one full r5_measure run")
    p.add_argument("--only", default="",
                   help="forwarded to r5_measure.py --only")
    p.add_argument("--rearm", action="store_true",
                   help="after a successful plan run, allow one re-run per "
                        "LATER live window (i.e. after the tunnel went "
                        "down and came back) instead of stopping at one")
    p.add_argument("--max-hours", type=float, default=13.0,
                   help="stop probing after this many hours")
    args = p.parse_args()

    deadline = time.monotonic() + args.max_hours * 3600.0
    measured = False
    was_live = False
    attempt = 0
    append({"event": "loop_start", "interval_s": args.interval})
    while time.monotonic() < deadline:
        attempt += 1
        row = probe(args.probe_timeout)
        append({"event": "probe", "attempt": attempt, **row})
        live = row.get("platform") == "tpu"
        # fire on a down->up transition (or the first live probe); --rearm
        # allows one re-run per LATER window, never back-to-back while the
        # tunnel simply stays up. A timed-out/failed plan leaves the
        # watchdog armed.
        if live and not was_live and (args.rearm or not measured):
            try:
                rc = measure(args.measure_timeout, args.only)
                measured = measured or rc == 0
            except subprocess.TimeoutExpired:
                append({"event": "measure_timeout"})
        was_live = live
        time.sleep(args.interval)
    append({"event": "loop_end", "attempts": attempt, "measured": measured})


if __name__ == "__main__":
    main()
