#!/usr/bin/env python
"""Op-level device profile of a bare tick at the bench shape.

Captures a jax.profiler trace of jitted ticks with state resident on
device (transfer-free, the same regime the bench measures), converts the
xplane with xprof, and prints the top HLO ops by self time — the "name the
dominant op" artifact BASELINE.md's optimization log cites.
``--scheduler exact`` profiles the bit-exact tick (``--exact-impl``
selects cascade/wave/fold) instead of the sync tick
(note: bare drained ticks deliver nothing, so for the cascade this shows
the selection/credit floor; the marker-fold cost only appears under live
traffic — use ``bench.py --profile`` for a full-storm trace).

Usage: python tools/profile_tick.py [--nodes N] [--batch B] [--ticks K]
       [--scheduler sync|exact] [--window-dtype int32|uint16]
       [--reduce-mode auto|matmul|segsum] [--out DIR]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def top_ops(trace_dir: str, limit: int) -> list:
    """Parse the captured xplane's hlo_stats (a gviz JSON table) into
    (self_us, pct, occurrences, category, bound_by, op expression) rows."""
    from xprof.convert import raw_to_tool_data

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [max(paths, key=os.path.getmtime)], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode(errors="replace")
    tbl = json.loads(data)
    ids = [c["id"] for c in tbl["cols"]]
    col = {name: ids.index(name) for name in (
        "category", "hlo_op_expression", "occurrences",
        "total_self_time", "total_self_time_percent", "bound_by")}
    rows = []
    for row in tbl["rows"]:
        c = [x.get("v") if x else None for x in row["c"]]
        rows.append((c[col["total_self_time"]] or 0.0,
                     c[col["total_self_time_percent"]] or 0.0,
                     c[col["occurrences"]] or 0,
                     c[col["category"]] or "",
                     c[col["bound_by"]] or "",
                     (c[col["hlo_op_expression"]] or "")[:110]))
    rows.sort(reverse=True)
    return rows[:limit]


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=1024)
    p.add_argument("--batch", type=int, default=2048)
    p.add_argument("--ticks", type=int, default=20)
    p.add_argument("--reduce-mode", default="auto",
                   choices=["auto", "matmul", "segsum"])
    p.add_argument("--scheduler", choices=["sync", "exact"], default="sync")
    p.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                   default="cascade",
                   help="--scheduler exact: tick formulation to profile")
    p.add_argument("--megatick", type=int, default=8,
                   help="--scheduler exact: K-tick fusion depth for the "
                        "per-stage megatick timing (ops/tick.TickKernel)")
    p.add_argument("--window-dtype", choices=["int32", "uint16"],
                   default="int32")
    p.add_argument("--layouts", choices=["auto", "default"], default="auto",
                   help="'auto' = XLA-chosen boundary layouts (same as "
                        "bench --layouts auto; the repeated-tick dispatch "
                        "reaches its layout fixed point after the warmup "
                        "call, so the timed/traced ticks are free of the "
                        "{0,2,1}<->{0,1,2} boundary copies — the in-scan "
                        "regime); 'default' = row-major boundaries (the "
                        "round-3 profile's 22%% copy lines) for A/B")
    p.add_argument("--queue-engine", choices=["auto", "gather", "mask"],
                   default="auto",
                   help="ring-queue addressing for the profiled kernel "
                        "(ops/tick.TickKernel; auto = backend-resolved); "
                        "the 'queue ops' section below times BOTH engines "
                        "regardless, so the O(E·C)->O(E) claim is "
                        "measured, not asserted")
    p.add_argument("--snapshots", type=int, default=8)
    p.add_argument("--fault-rate", type=float, default=0.01,
                   help="per-class rate for the 'faults' overhead section "
                        "(models/faults.py): the masked-adversary cost on "
                        "the hot path is measured at faults=off / "
                        "zero-rate (instrumented, all-False masks) / this "
                        "active rate")
    p.add_argument("--delay", choices=["uniform", "hash"], default="hash",
                   help="same knob as bench --delay")
    p.add_argument("--out", default="/tmp/tickprof")
    p.add_argument("--top", type=int, default=18)
    args = p.parse_args()

    import jax
    import numpy as np

    # same contract as maxbatch.py: the env var alone cannot override this
    # image's TPU plugin, so CLSIM_PLATFORM=cpu must go through jax.config
    platform = os.environ.get("CLSIM_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import scale_free
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    cfg = SimConfig.for_workload(snapshots=args.snapshots, max_recorded=16,
                                 record_dtype="int16",
                                 window_dtype=args.window_dtype,
                                 reduce_mode=args.reduce_mode,
                                 split_markers=args.scheduler == "sync")
    spec = scale_free(args.nodes, 2, seed=3, tokens=100)
    runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, 17),
                           batch=args.batch, scheduler=args.scheduler,
                           exact_impl=args.exact_impl,
                           megatick=args.megatick,
                           queue_engine=args.queue_engine)
    print(f"N={runner.topo.n} E={runner.topo.e} B={args.batch} "
          f"scheduler={args.scheduler} mode={runner.kernel._mode}",
          file=sys.stderr)

    # donation matches the production jits (TickKernel.tick / run_storm):
    # without it the profiled executable cannot alias state buffers and
    # runs in a different (2x-resident) HBM regime than the bench
    from chandy_lamport_tpu.utils.layouts import (
        HAVE_LAYOUTS,
        array_format,
        auto_format,
        format_layout,
    )

    jit_kw = {"donate_argnums": 0}
    if args.layouts == "auto" and not HAVE_LAYOUTS:
        print("auto layouts unavailable in this jax build; profiling "
              "row-major boundaries", file=sys.stderr)
        args.layouts = "default"
    if args.layouts == "auto":
        fmt = auto_format()
        jit_kw.update(in_shardings=fmt, out_shardings=fmt)
    tick = jax.jit(jax.vmap(runner._tick_fn), **jit_kw)
    s = runner.init_batch_device()
    s = tick(s)
    # with auto layouts the output state carries the compiler-chosen
    # formats; feeding it back reaches the copy-free fixed point, so the
    # timed loop below measures the same regime as the storm scan's
    # interior. Report what AUTO actually chose as evidence.
    jax.block_until_ready(s)
    if args.layouts == "auto":
        nondefault = [
            f"{np.shape(x)}:{format_layout(array_format(x)).major_to_minor}"
            for x in jax.tree_util.tree_leaves(s)
            if array_format(x) is not None and np.ndim(x) > 0
            and format_layout(array_format(x)).major_to_minor
            != tuple(range(np.ndim(x)))]
        print(f"auto layouts: {len(nondefault)} non-row-major state "
              f"leaves {nondefault[:6]}", file=sys.stderr)
    s = tick(s)
    jax.block_until_ready(s)

    t0 = time.perf_counter()
    for _ in range(args.ticks):
        s = tick(s)
    jax.block_until_ready(s)
    per_tick = (time.perf_counter() - t0) / args.ticks
    print(f"per-tick (untraced): {per_tick * 1e3:.2f} ms -> "
          f"{args.batch * runner.topo.n / per_tick / 1e6:.1f}M node-ticks/s",
          file=sys.stderr)

    # ---- queue ops A/B: the PR-2 claim, measured ------------------------
    # Per-primitive wall clock of the three ring-queue operations under
    # BOTH addressings (ops/tick.TickKernel queue_engine): "gather" = O(E)
    # take_along_axis head reads + .at[edge, pos] append scatters over the
    # packed planes; "mask" = the legacy [E, C] one-hot reductions/selects
    # whose HBM traffic scales with queue CAPACITY. Same state, same
    # shapes — only the addressing differs.
    from chandy_lamport_tpu.ops.tick import TickKernel

    reps = max(args.ticks, 10)
    qtimings = {}
    for engine in ("gather", "mask"):
        k_eng = (runner.kernel if engine == runner.kernel.queue_engine
                 else TickKernel(runner.topo, runner.config, runner.delay,
                                 marker_mode=runner.kernel.marker_mode,
                                 exact_impl=args.exact_impl,
                                 megatick=args.megatick,
                                 queue_engine=engine))

        def head_select(t, k=k_eng):
            rt, mk, data = k._head_fields(t)
            return rt + data + mk          # keep all three reads live

        def select_pop(t, k=k_eng):
            t = t._replace(time=t.time + 1)
            return k._select_and_pop(t)[0]

        def append_all(t, k=k_eng):
            active = jax.numpy.ones(k.topo.e, bool)
            return k._append_rows(t, active, t.time + 1, False,
                                  jax.numpy.int32(1))

        for name, fn in (("head-select", head_select),
                         ("pop", select_pop), ("append", append_all)):
            jfn = jax.jit(jax.vmap(fn))
            st = runner.init_batch_device()
            out = jfn(st)                  # compile + warm
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = jfn(st)
            jax.block_until_ready(out)
            qtimings[(engine, name)] = (time.perf_counter() - t0) / reps
    print("queue ops (per call, both addressings):", file=sys.stderr)
    print(f"  {'op':<12} {'gather ms':>10} {'mask ms':>10} {'speedup':>8}",
          file=sys.stderr)
    for name in ("head-select", "pop", "append"):
        g = qtimings[("gather", name)]
        m = qtimings[("mask", name)]
        print(f"  {name:<12} {g * 1e3:10.3f} {m * 1e3:10.3f} "
              f"{m / g:7.2f}x", file=sys.stderr)

    # ---- kernel engines A/B: the Pallas-fusion claim, measured ----------
    # Per-op wall clock of the tick's hot ops under BOTH tick-kernel
    # engines (SimConfig.kernel_engine): "xla" = the stock formulations,
    # "pallas" = the fused VMEM-resident kernels (chandy_lamport_tpu/
    # kernels). Off-TPU the pallas column is interpret-mode EMULATION —
    # expect it to lose badly there; the comparison is about the TPU
    # regime, the CPU run just proves both paths execute. Same state,
    # same shapes — only the engine differs.
    ketimings = {}
    for engine in ("xla", "pallas"):
        k_ke = (runner.kernel if engine == runner.kernel.kernel_engine
                else TickKernel(runner.topo, runner.config, runner.delay,
                                marker_mode=runner.kernel.marker_mode,
                                exact_impl=args.exact_impl,
                                megatick=args.megatick,
                                queue_engine=args.queue_engine,
                                kernel_engine=engine))

        def queue_step(t, k=k_ke):
            t = t._replace(time=t.time + 1)
            return k._select_and_pop(t)[0]

        def seg_reduce(t, k=k_ke):
            credit = k._sum_by_dst(t.q_len > 0, amounts=False)
            return k._spread_dst(credit > 0)

        ktick = (k_ke._sync_tick if args.scheduler == "sync"
                 else k_ke._exact_tick)
        for name, fn in (("queue-step", queue_step),
                         ("seg-reduce", seg_reduce),
                         ("full-tick", ktick)):
            jfn = jax.jit(jax.vmap(fn))
            st = runner.init_batch_device()
            out = jfn(st)                  # compile + warm
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = jfn(st)
            jax.block_until_ready(out)
            ketimings[(engine, name)] = (time.perf_counter() - t0) / reps
    note = ("" if dev.platform == "tpu"
            else "; pallas is interpret-mode emulation here")
    print(f"kernels (per call, both engines{note}):", file=sys.stderr)
    print(f"  {'op':<12} {'xla ms':>10} {'pallas ms':>10} {'speedup':>8}",
          file=sys.stderr)
    for name in ("queue-step", "seg-reduce", "full-tick"):
        x = ketimings[("xla", name)]
        pl_t = ketimings[("pallas", name)]
        print(f"  {name:<12} {x * 1e3:10.3f} {pl_t * 1e3:10.3f} "
              f"{x / pl_t:7.2f}x", file=sys.stderr)

    # ---- megakernel A/B: the one-kernel-megatick claim, measured --------
    # Wall clock of a K-tick dispatch (`run_ticks(K)`, megatick=K) under
    # three arms per K: "xla" = the stock formulations, "split" = the
    # per-stage Pallas kernels (kernel_engine=pallas, fused_tick=off),
    # "fused" = the one-kernel megatick (kernels/megatick.py: the whole
    # K-tick loop as ONE kernel, state VMEM-resident between ticks).
    # Off-TPU both Pallas columns are interpret-mode emulation — the
    # comparison is about the TPU regime, where the fused arm's HBM
    # round trips drop to ~1/K of split's (the cost plane's
    # hbm_model_bytes metric pins exactly this). K=1 has no fused arm by
    # construction (resolve_fused_tick requires megatick > 1).
    mk_impl = (args.exact_impl if args.exact_impl in ("cascade", "wave")
               else "cascade")
    # the fused arms need the unified marker ring; under --scheduler sync
    # the main runner's states carry split-marker planes, so the section
    # gets its own exact-mode runner (same graph, same delay stream)
    mk_runner = (runner if args.scheduler == "exact" else BatchedRunner(
        spec, SimConfig.for_workload(
            snapshots=args.snapshots, max_recorded=16,
            record_dtype="int16", window_dtype=args.window_dtype,
            reduce_mode=args.reduce_mode),
        make_fast_delay(args.delay, 17), batch=args.batch,
        scheduler="exact", exact_impl=mk_impl,
        queue_engine=args.queue_engine))
    mktimings = {}
    for k_ticks in (1, 4, 16):
        for arm, (engine, fused) in (("xla", ("xla", "off")),
                                     ("split", ("pallas", "off")),
                                     ("fused", ("pallas", "on"))):
            if arm == "fused" and k_ticks == 1:
                continue
            k_mk = TickKernel(mk_runner.topo, mk_runner.config,
                              mk_runner.delay,
                              marker_mode="ring", exact_impl=mk_impl,
                              megatick=k_ticks, queue_engine=args.queue_engine,
                              kernel_engine=engine, fused_tick=fused)
            jfn = jax.jit(jax.vmap(
                lambda t, k=k_mk, n=k_ticks: k._run_ticks(
                    t, jax.numpy.int32(n))))
            st = mk_runner.init_batch_device()
            out = jfn(st)                  # compile + warm
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = jfn(st)
            jax.block_until_ready(out)
            mktimings[(k_ticks, arm)] = (time.perf_counter() - t0) / reps
    # honesty stamp (bench rows carry the same field): off-TPU the fused
    # column is interpret-mode Pallas — a CPU gauge, not a TPU fused win
    mk_emulated = dev.platform != "tpu"
    print(f"megakernel (run_ticks(K) per dispatch, impl={mk_impl}, "
          f"fused_emulated={str(mk_emulated).lower()}{note}):",
          file=sys.stderr)
    print(f"  {'K':<4} {'xla ms':>10} {'split ms':>10} {'fused ms':>10} "
          f"{'fused vs split':>14}", file=sys.stderr)
    for k_ticks in (1, 4, 16):
        x = mktimings[(k_ticks, "xla")]
        sp = mktimings[(k_ticks, "split")]
        fu = mktimings.get((k_ticks, "fused"))
        fused_col = f"{fu * 1e3:10.3f}" if fu is not None else f"{'—':>10}"
        ratio = f"{sp / fu:13.2f}x" if fu is not None else f"{'n/a':>14}"
        print(f"  {k_ticks:<4} {x * 1e3:10.3f} {sp * 1e3:10.3f} "
              f"{fused_col} {ratio}", file=sys.stderr)

    # ---- refill: the streaming engine's harvest + admit tax, measured ---
    # Per-step cost of continuous lane scheduling (parallel/batch.
    # _build_stream_step): the full jitted stream step — harvest retiring
    # lanes into the results ring, admit queued jobs into the freed slots,
    # then `stretch` script phases + one drain slice + one flush pass per
    # lane — next to its two refill-only primitives in isolation:
    # harvest_lane_summaries (the [B] per-lane summary reductions) and
    # reset_lanes (the masked fresh-template scatter over every state
    # leaf). The deltas bound what slot recycling adds on top of the
    # phase work the step would do anyway (~stretch+chunk+flush ticks).
    from chandy_lamport_tpu.models.workloads import stream_jobs
    from chandy_lamport_tpu.ops.tick import (
        harvest_lane_summaries,
        reset_lanes,
    )

    r_stretch, r_chunk = 4, 8
    jobs = stream_jobs(spec, 2 * args.batch, seed=17, base_phases=4,
                       max_phases=16)
    pool = runner.pack_jobs(jobs)
    pool_dev = jax.tree_util.tree_map(jax.numpy.asarray, pool)
    half = jax.numpy.arange(args.batch) % 2 == 0

    jharv = jax.jit(lambda t: harvest_lane_summaries(t, runner.topo.n))
    jreset = jax.jit(lambda t: reset_lanes(t, half, runner.topo, cfg),
                     donate_argnums=0)
    sstep = runner._stream_step(r_stretch, r_chunk, False)

    rtimings = {}
    st = runner.init_batch_device()
    out = jharv(st)                            # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jharv(st)
    jax.block_until_ready(out)
    rtimings["harvest"] = (time.perf_counter() - t0) / reps

    st = jreset(runner.init_batch_device())    # compile + warm
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    for _ in range(reps):
        st = jreset(st)
    jax.block_until_ready(st)
    rtimings["lane-reset"] = (time.perf_counter() - t0) / reps

    st, sm = runner.init_batch(), runner.init_stream(pool)
    st, sm = sstep(st, sm, pool_dev)           # compile + warm
    jax.block_until_ready(st.time)
    t0 = time.perf_counter()
    for _ in range(reps):
        st, sm = sstep(st, sm, pool_dev)
    jax.block_until_ready(st.time)
    rtimings["stream-step"] = (time.perf_counter() - t0) / reps

    work = r_stretch + r_chunk + cfg.max_delay + 1
    print(f"refill (streaming engine, stretch={r_stretch} "
          f"drain_chunk={r_chunk}):", file=sys.stderr)
    print(f"  harvest summaries        "
          f"{rtimings['harvest'] * 1e3:9.3f} ms", file=sys.stderr)
    print(f"  lane reset (half mask)   "
          f"{rtimings['lane-reset'] * 1e3:9.3f} ms", file=sys.stderr)
    print(f"  full stream step         "
          f"{rtimings['stream-step'] * 1e3:9.3f} ms "
          f"(~{work} lane-ticks of phase work; bare tick "
          f"{per_tick * 1e3:.3f} ms)", file=sys.stderr)

    # ---- fault-adversary overhead: the compiled-in-zero-cost claim, -----
    # measured. Three kernels at the same shape: faults=None (the
    # uninstrumented trace), a zero-rate JaxFaults (instrumentation in the
    # trace, every mask False — the pure hash/mask tax), and an active
    # adversary (drop/dup/jitter at --fault-rate plus lossy crash windows,
    # which also void the exact path's quiescence fast-forward).
    if args.scheduler == "exact" and args.exact_impl == "fold":
        print("faults: skipped (exact_impl='fold' is the reference-literal "
              "specification form and runs uninjured)", file=sys.stderr)
    else:
        from chandy_lamport_tpu.models.faults import JaxFaults

        r = args.fault_rate
        fvariants = [
            ("off", None),
            ("zero-rate", JaxFaults(7)),
            ("active", JaxFaults(7, drop_rate=r, dup_rate=r, jitter_rate=r,
                                 crash_rate=r, crash_mode="lossy")),
        ]
        ftimings = {}
        for fname, f in fvariants:
            fr = (runner if f is None else
                  BatchedRunner(spec, cfg, make_fast_delay(args.delay, 17),
                                batch=args.batch, scheduler=args.scheduler,
                                exact_impl=args.exact_impl,
                                megatick=args.megatick,
                                queue_engine=args.queue_engine, faults=f))
            ftick = jax.jit(jax.vmap(fr._tick_fn), donate_argnums=0)
            st = fr.init_batch_device()
            st = ftick(st)                        # compile + warm
            jax.block_until_ready(st)
            t0 = time.perf_counter()
            for _ in range(args.ticks):
                st = ftick(st)
            jax.block_until_ready(st)
            ftimings[fname] = (time.perf_counter() - t0) / args.ticks
        base = ftimings["off"]
        print(f"faults (masked-adversary overhead, rate={r}):",
              file=sys.stderr)
        for fname, _ in fvariants:
            t = ftimings[fname]
            print(f"  {fname:<10} {t * 1e3:9.3f} ms/tick "
                  f"({(t / base - 1) * 100:+6.2f}% vs off)", file=sys.stderr)

    # ---- supervisor overhead: the epoch-check + timeout-scan tax, -------
    # measured (the PR-3 faults section's pattern). Three kernels at the
    # same shape: supervisor off (zero supervisor ops in the trace),
    # armed-idle (timeout huge — the pure scan/clear/epoch-decode cost with
    # nothing ever firing), and active (tight timeout + the snapshot_every
    # daemon, so aborts/retries/initiations actually run).
    if args.scheduler == "exact" and args.exact_impl == "fold":
        print("supervisor: skipped (exact_impl='fold' is the reference-"
              "literal specification form and carries no supervisor)",
              file=sys.stderr)
    else:
        import dataclasses

        svariants = [
            ("off", {}),
            ("armed-idle", {"snapshot_timeout": 1 << 20,
                            "snapshot_retries": 3}),
            ("active", {"snapshot_timeout": 8, "snapshot_retries": 3,
                        "snapshot_every": 16}),
        ]
        stimings = {}
        for sname, patch in svariants:
            sr = (runner if not patch else
                  BatchedRunner(spec, dataclasses.replace(cfg, **patch),
                                make_fast_delay(args.delay, 17),
                                batch=args.batch, scheduler=args.scheduler,
                                exact_impl=args.exact_impl,
                                megatick=args.megatick,
                                queue_engine=args.queue_engine))
            stick = jax.jit(jax.vmap(sr._tick_fn), donate_argnums=0)
            st = sr.init_batch_device()
            st = stick(st)                        # compile + warm
            jax.block_until_ready(st)
            t0 = time.perf_counter()
            for _ in range(args.ticks):
                st = stick(st)
            jax.block_until_ready(st)
            stimings[sname] = (time.perf_counter() - t0) / args.ticks
        sbase = stimings["off"]
        print("supervisor (timeout-scan + epoch-check overhead):",
              file=sys.stderr)
        for sname, _ in svariants:
            t = stimings[sname]
            print(f"  {sname:<10} {t * 1e3:9.3f} ms/tick "
                  f"({(t / sbase - 1) * 100:+6.2f}% vs off)",
                  file=sys.stderr)

    # ---- flight-recorder overhead: the compiled-away-when-off claim, ----
    # measured (same template as the faults section). Three kernels at the
    # same shape: trace off (trace=None — the ring writes do not exist in
    # the compiled kernel, the bit-identity tests/test_trace.py pins), armed
    # but runtime-disarmed (tr_on=0: every scatter still compiled in, every
    # append mask forced False — the pure instruction tax), and recording
    # (tr_on=1, events landing in the ring every tick).
    from chandy_lamport_tpu.utils.tracing import JaxTrace

    tr_runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, 17),
                              batch=args.batch, scheduler=args.scheduler,
                              exact_impl=args.exact_impl,
                              megatick=args.megatick,
                              queue_engine=args.queue_engine,
                              trace=JaxTrace())
    ttick = jax.jit(jax.vmap(tr_runner._tick_fn), donate_argnums=0)
    ttimings = {"off": per_tick}
    for tname, armed in (("armed-idle", 0), ("recording", 1)):
        st = tr_runner.init_batch_device()
        st = st._replace(tr_on=jax.numpy.full_like(st.tr_on, armed))
        st = ttick(st)                            # compile + warm
        jax.block_until_ready(st)
        t0 = time.perf_counter()
        for _ in range(args.ticks):
            st = ttick(st)
        jax.block_until_ready(st)
        ttimings[tname] = (time.perf_counter() - t0) / args.ticks
    print(f"flight recorder (ring writes on the hot path, "
          f"K={tr_runner.config.trace_capacity}):", file=sys.stderr)
    for tname in ("off", "armed-idle", "recording"):
        t = ttimings[tname]
        print(f"  {tname:<12} {t * 1e3:9.3f} ms/tick "
              f"({(t / per_tick - 1) * 100:+6.2f}% vs off)",
              file=sys.stderr)

    # ---- graphshard comm A/B: dense plane vs sparse halo exchange, ------
    # measured. One sharded sync tick (GraphShardedRunner.jit_tick) at
    # the gauge shape under comm_engine=dense (full-plane psum/all_gather
    # + incidence matmuls) and sparse (O(E_local) segment sums + boundary
    # ppermutes), next to a single-shard mesh (P=1: collectives
    # degenerate — the collective-formulation floor). Runs on however
    # many devices are visible (the 8-device CPU mesh under
    # XLA_FLAGS=--xla_force_host_platform_device_count=8); gracefully
    # skipped when the mesh cannot shard (<2 devices).
    n_dev = len(jax.devices())
    gsh = max((k for k in (2, 4, 8)
               if k <= n_dev and args.nodes % k == 0), default=0)
    if gsh < 2:
        print(f"graphshard comm: skipped ({n_dev} device(s) visible; "
              f"need >=2 dividing --nodes {args.nodes})", file=sys.stderr)
    else:
        from jax.sharding import Mesh

        from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner

        devs = jax.devices()
        gtimings = {}
        model = None
        for gname, shards, engine in (
                ("dense", gsh, "dense"), ("sparse", gsh, "sparse"),
                ("single-shard", 1, "sparse")):
            gmesh = Mesh(np.array(devs[:shards]), ("graph",))
            gr = GraphShardedRunner(spec, cfg, gmesh, seed=17,
                                    fixed_delay=2, comm_engine=engine,
                                    queue_engine=args.queue_engine)
            if engine == "sparse" and shards == gsh:
                model = gr.comm_model()
            gtick = gr.jit_tick()
            stopo = gr.stopo_device()
            gs = gtick(gr.init_state(), stopo)     # compile + warm
            jax.block_until_ready(gs)
            t0 = time.perf_counter()
            for _ in range(reps):
                gs = gtick(gs, stopo)
            jax.block_until_ready(gs)
            gtimings[gname] = (time.perf_counter() - t0) / reps
        print(f"graphshard comm (one sharded sync tick, N={args.nodes} "
              f"P={gsh}):", file=sys.stderr)
        for gname in ("dense", "sparse", "single-shard"):
            t = gtimings[gname]
            print(f"  {gname:<12} {t * 1e3:9.3f} ms/tick "
                  f"({gtimings['dense'] / t:5.2f}x vs dense)",
                  file=sys.stderr)
        print(f"  byte model: dense {model['dense_bytes_per_tick']} B "
              f"sparse {model['sparse_bytes_per_tick']} B per shard-tick "
              f"(ratio {model['sparse_over_dense']}, "
              f"halo {model['halo_rows']} rows x {model['neighbors']} "
              f"neighbors)", file=sys.stderr)

    if args.scheduler == "exact":
        # per-stage wall-clock of the fused exact path: how much of a
        # dispatch is tick-start delivery selection (_select_and_pop, the
        # shared cascade/wave front half) vs the sequential marker phase
        # (full tick minus selection) vs the K-tick megatick's scan glue
        # (amortized per-tick megatick cost vs a bare tick). Bare drained
        # states deliver nothing, so this is the selection/fold floor —
        # use bench.py --profile for a live-traffic trace.
        import jax.numpy as jnp

        k = runner.kernel

        def select_only(t):
            t = t._replace(time=t.time + 1)
            return k._select_and_pop(t)[0]

        stages = [
            ("delivery-select", jax.jit(jax.vmap(select_only),
                                        donate_argnums=0)),
            ("full-tick", jax.jit(jax.vmap(runner._tick_fn),
                                  donate_argnums=0)),
            (f"megatick-x{k.megatick}", jax.jit(
                jax.vmap(lambda t: k._run_ticks(t, jnp.int32(k.megatick))),
                donate_argnums=0)),
        ]
        timings = {}
        for name, fn in stages:
            st = runner.init_batch_device()
            st = fn(st)                      # compile + warm
            jax.block_until_ready(st)
            t0 = time.perf_counter()
            for _ in range(max(args.ticks // 2, 3)):
                st = fn(st)
            jax.block_until_ready(st)
            timings[name] = ((time.perf_counter() - t0)
                             / max(args.ticks // 2, 3))
        sel = timings["delivery-select"]
        full = timings["full-tick"]
        mega = timings[f"megatick-x{k.megatick}"]
        print(f"exact-stage breakdown ({args.exact_impl}):", file=sys.stderr)
        print(f"  delivery-select          {sel * 1e3:9.3f} ms",
              file=sys.stderr)
        print(f"  marker phase (full-sel)  {(full - sel) * 1e3:9.3f} ms",
              file=sys.stderr)
        print(f"  megatick x{k.megatick} per tick     "
              f"{mega / k.megatick * 1e3:9.3f} ms "
              f"(dispatch {mega * 1e3:.3f} ms, bare tick "
              f"{full * 1e3:.3f} ms)", file=sys.stderr)

    jax.profiler.start_trace(args.out)
    for _ in range(args.ticks):
        s = tick(s)
    jax.block_until_ready(s)
    jax.profiler.stop_trace()

    try:
        rows = top_ops(args.out, args.top)
    except Exception as exc:  # xprof not installed / conversion failed:
        # the wall-clock sections above already printed — keep the trace
        print(f"hlo_stats unavailable ({type(exc).__name__}: {exc}); "
              f"raw trace kept under {args.out}", file=sys.stderr)
        return
    print(f"{'self ms':>9} {'%':>6} {'x':>5}  cat/bound  op")
    for self_us, pct, occ, cat, bound, expr in rows:
        print(f"{self_us / 1e3:9.2f} {pct:6.2f} {occ:5}  {cat}/{bound}  {expr}")


if __name__ == "__main__":
    main()
