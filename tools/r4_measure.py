#!/usr/bin/env python
"""The round-4 TPU measurement plan, one command.

Runs every row VERDICT r3 asked for against the live device and appends to
``BASELINE_MEASURED.jsonl`` (same JSON contract as bench.py/ladder.py —
every row carries platform/device_kind, clamped shapes are labeled):

  1. sync ladder refresh (configs 2-5 + the literal 1M-instance north star)
  2. cascade-exact ladder at FULL batches — the cascade tick (ops/tick
     _cascade_tick) removes the N-step per-tick fold, so exact no longer
     needs clamped batches, and N=8192 must now compile+run on device
     (VERDICT r3 #2)
  3. "exact semantics at scale": the reference scheduler with per-lane
     hash-delay streams at production widths (VERDICT r3 #3)
  4. graphshard overhead: config-4 shape, unsharded B=1 vs --graphshard 1
     on the same chip (VERDICT r3 #4)
  5. max-batch presets northstar/config3/config4 with the HBM axis
     (VERDICT r3 #6)
  6. window-dtype A/B at the headline config: uint16 window planes vs the
     int32 default (VERDICT r3 #7 — the [S, E] window-counter writes are
     the top profile line; flip the bench default if uint16 wins)
  7. boundary-layout A/B at the headline config: --layouts default (the
     round-3/4 row-major boundaries) vs step 1's row, which rides the
     new --layouts auto default (VERDICT r4 #6 — the {0,2,1}<->{0,1,2}
     jit-boundary transposes were 22% of a bare tick)

Usage: python tools/r4_measure.py [--only 1,2,...] [--timeout S]
Skips nothing silently: a failed row still appends its error JSON.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def run_tool(name: str, script: str, extra: list, timeout: float, out: str) -> dict:
    cmd = [sys.executable, os.path.join(ROOT, script)] + extra
    log(f"--- {name}: {' '.join(cmd)}")
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, cwd=ROOT,
                              timeout=timeout)
        lines = proc.stdout.decode().strip().splitlines()
        row = (json.loads(lines[-1]) if lines
               else {"error": "no output", "rc": proc.returncode})
    except subprocess.TimeoutExpired:
        row = {"error": f"timed out after {timeout:.0f}s"}
    except Exception as exc:  # a malformed row must not kill the plan
        row = {"error": f"{type(exc).__name__}: {exc}"}
    row["config"] = name
    print(json.dumps(row), flush=True)
    with open(out, "a") as f:
        f.write(json.dumps(row) + "\n")
    return row


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--only", default="",
                   help="comma-separated step numbers (default: all)")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="bench-internal full-size attempt budget")
    p.add_argument("--out", default=os.path.join(ROOT, "BASELINE_MEASURED.jsonl"))
    args = p.parse_args()
    only = {int(x) for x in args.only.split(",") if x} or set(range(1, 8))

    def bench(name, extra):
        # outer budget: probe ladder + attempts; bench always prints a line
        return run_tool(name, "bench.py",
                        extra + ["--timeout", str(args.timeout)],
                        args.timeout * 3 + 600, args.out)

    # headline config FIRST: if the tunnel window is short, the one row
    # that validates the current code on hardware (and is what the driver's
    # own bench will run) must land before the nice-to-have refreshes
    plan_sync = [
        ("r4_config4_sf1k_sync", ["--graph", "sf", "--nodes", "1024",
                                  "--batch", "2048", "--phases", "32",
                                  "--snapshots", "8"]),
        ("r4_northstar_ring10_1M", ["--graph", "ring", "--nodes", "10",
                                    "--batch", "1048576", "--phases", "32",
                                    "--snapshots", "2", "--repeats", "2"]),
        ("r4_config2_ring10_sync", ["--graph", "ring", "--nodes", "10",
                                    "--batch", "131072", "--phases", "32",
                                    "--snapshots", "1"]),
        ("r4_config3_er256_sync", ["--graph", "er", "--nodes", "256",
                                   "--batch", "4096", "--phases", "32",
                                   "--snapshots", "4"]),
        ("r4_config5_sf8k_sync", ["--graph", "sf", "--nodes", "8192",
                                  "--batch", "512", "--phases", "16",
                                  "--snapshots", "8"]),
    ]
    # cascade exact at the SYNC batches — the whole point of the cascade
    # (config 5 included: the N=8192 device fault must be gone; configs 2-3
    # are covered by step 3's explicitly-labeled exact-at-scale rows, since
    # bench's default delay is already the per-lane hash stream)
    plan_exact = [
        ("r4_config4_sf1k_exact", ["--graph", "sf", "--nodes", "1024",
                                   "--batch", "2048", "--phases", "32",
                                   "--snapshots", "8"]),
        ("r4_config5_sf8k_exact", ["--graph", "sf", "--nodes", "8192",
                                   "--batch", "512", "--phases", "16",
                                   "--snapshots", "8"]),
    ]

    if 1 in only:
        for name, extra in plan_sync:
            bench(name, extra + ["--scheduler", "sync"])
    if 2 in only:
        for name, extra in plan_exact:
            bench(name, extra + ["--scheduler", "exact"])
    if 3 in only:
        # "exact semantics at scale": reference scheduler, per-lane hash
        # streams, production widths (the GoExact shared stream is only
        # required for golden conformance)
        bench("r4_exact_at_scale_ring10",
              ["--graph", "ring", "--nodes", "10", "--batch", "131072",
               "--phases", "32", "--snapshots", "1",
               "--scheduler", "exact", "--delay", "hash"])
        bench("r4_exact_at_scale_er256",
              ["--graph", "er", "--nodes", "256", "--batch", "4096",
               "--phases", "32", "--snapshots", "4",
               "--scheduler", "exact", "--delay", "hash"])
    if 4 in only:
        # collective-formulation tax: same shape, unsharded B=1 vs 1-shard
        bench("r4_gshard_base_sf1k_b1",
              ["--graph", "sf", "--nodes", "1024", "--batch", "1",
               "--phases", "32", "--snapshots", "8", "--scheduler", "sync"])
        bench("r4_gshard_1shard_sf1k",
              ["--graph", "sf", "--nodes", "1024", "--graphshard", "1",
               "--phases", "32", "--snapshots", "8"])
    if 5 in only:
        for preset in ("northstar", "config3", "config4"):
            run_tool(f"r4_maxbatch_{preset}", "tools/maxbatch.py",
                     ["--preset", preset, "--record-dtype", "int16"],
                     3600.0, args.out)
    if 6 in only:
        # A/B the uint16 window planes at the headline config (the int32
        # side is step 1's r4_config4_sf1k_sync row)
        bench("r4_config4_sf1k_sync_win16",
              ["--graph", "sf", "--nodes", "1024", "--batch", "2048",
               "--phases", "32", "--snapshots", "8", "--scheduler", "sync",
               "--window-dtype", "uint16"])
    if 7 in only:
        # boundary-layout A/B: forced row-major boundaries vs step 1's
        # --layouts auto default (VERDICT r4 #6)
        bench("r4_config4_sf1k_sync_rowmajor",
              ["--graph", "sf", "--nodes", "1024", "--batch", "2048",
               "--phases", "32", "--snapshots", "8", "--scheduler", "sync",
               "--layouts", "default"])
    log("r4 measurement plan complete")


if __name__ == "__main__":
    main()
