#!/usr/bin/env python
"""The round-5 TPU measurement plan, one command.

Round 5 finally caught a live tunnel window (2026-07-30 ~20:56-21:04 UTC)
and banked five sync rows — headline config-4 at 120.5M, the 1M-instance
north star at 256.7M (25.7x target) — before the tunnel wedged mid-plan.
Three rows died on the auto-layout ``input_formats`` bug (fixed since:
parallel/batch.py relayouts through compiled identities and falls back to
row-major boundaries on rejection) and the rest never ran.  This plan
records everything still missing, ordered by value-per-tunnel-second in
case the next window is short:

  1. on-device golden conformance of the cascade-exact scheduler
     (VERDICT r4 #2): the 7 test_data/ goldens bit-exact through the jax
     backend ON the TPU.  Semantics carried:
     /root/reference/chandy_lamport/node.go:149-185, sim.go:76-92.
  6. "exact semantics >= 10M" at scale (VERDICT r4 #3) — promoted ahead
     of everything else: it is the twice-carried verdict item and the
     observed tunnel windows fit only ~2-5 rows. Ring-10 B=131k runs
     first (its low marker density is what clears the 10M bar — a CPU
     gauge put the marker-heavy ER-256 half at 13.9k/s at B=256; the
     ring row's warmup wedged the 2026-07-30 window on pre-fix code, so
     it gets a bounded 420s budget), then the ER-256 half as a
     cascade/wave A/B pair (the wave-exact tick measured 15.4x the
     cascade on the CPU gauge at this marker density, bit-identical).
  4. cascade exact at config 4 full batch, plus a reduced N=8192 proof
     row — the shape that faulted the round-3 device must run clean
     (VERDICT r4 #2; the FULL config-5 exact shape costs ~196k
     sequential marker steps, longer than a whole tunnel window, and
     runs dead last in step 9 instead).
  5. the one sync ladder row the wedge ate: config-2 ring-10 B=131072.
  2. boundary-layout A/B at the headline config (VERDICT r4 #6):
     --layouts default vs auto. Banked same-window 2026-07-31 03:18Z:
     119.97M row-major vs 120.99M auto (+0.9% auto).
  3. uint16 window-plane A/B at the headline config (VERDICT r4 #5),
     paired with a same-window auto baseline. Demoted behind the exact
     rows 2026-07-31: its first on-device compile sat >840s and the
     window died under it; a re-fire must not let it eat the next
     window before the exact rows run.
  7. graphshard formulation tax on real ICI (VERDICT r4 weak #5).
  8. maxbatch presets with the HBM axis (VERDICT r4 #8).
  9. the riskiest row dead last: the full ladder-shape config-5 exact
     row (~196k sequential marker steps, likely longer than a whole
     window). A wedge here costs nothing else.

The plan is resumable: a step whose full-shape on-device row is already
in ``--out`` is skipped on re-fire (probe_loop --rearm), and when a row
comes back non-TPU the plan re-probes the tunnel — if the tunnel is gone
it stops immediately (exit 3) instead of burning the remaining rows'
fallback ladders against a wedged device; a deterministic single-row
failure with the tunnel alive does NOT stop the plan.

Usage: python tools/r5_measure.py [--only 1,2,...] [--timeout S]
Every row (including failures) appends to BASELINE_MEASURED.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def run_tool(name: str, script: str, extra: list, timeout: float, out: str,
             argv0: list = None, env: dict = None,
             parse=None) -> dict:
    """Run one plan step and append its row (stamped with UTC time, so
    cross-window pairs are distinguishable). ``parse`` maps a finished
    process to a row dict (default: the last stdout line as JSON)."""
    import datetime
    cmd = (argv0 or [sys.executable, os.path.join(ROOT, script)]) + extra
    log(f"--- {name}: {' '.join(cmd)}")
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT if parse else None,
                              cwd=ROOT, timeout=timeout, env=env)
        if parse:
            row = parse(proc)
        else:
            lines = proc.stdout.decode().strip().splitlines()
            row = (json.loads(lines[-1]) if lines
                   else {"error": "no output", "rc": proc.returncode})
    except subprocess.TimeoutExpired:
        row = {"error": f"timed out after {timeout:.0f}s"}
    except Exception as exc:  # a malformed row must not kill the plan
        row = {"error": f"{type(exc).__name__}: {exc}"}
    row["config"] = name
    row["ts"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    print(json.dumps(row), flush=True)
    with open(out, "a") as f:
        f.write(json.dumps(row) + "\n")
    return row


def tunnel_alive(timeout: float = 120.0) -> bool:
    """The bench's own liveness probe, used to distinguish 'this row fails
    deterministically' from 'the tunnel died under the plan'."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "chandy_lamport_tpu.bench", "--probe"],
            stdout=subprocess.PIPE, cwd=ROOT, timeout=timeout)
        lines = proc.stdout.decode().strip().splitlines()
        return bool(lines) and \
            json.loads(lines[-1]).get("platform") == "tpu"
    except Exception:
        return False


def conformance(timeout: float, out: str) -> dict:
    """Run the 7-golden CLI conformance suite on the live device (the CLI
    refuses bit-exact mode without x64) and append a pass/fail row. The
    CLI prints the executing platform after the verdict; it is parsed
    into the row so a CPU run can never bank the on-device claim."""
    def parse(proc):
        tail = proc.stdout.decode().strip().splitlines()[-9:]
        platform = ""
        for line in tail:
            if line.startswith("platform: "):
                platform = line.split()[1]
        return {"metric": "golden_conformance_on_device",
                "ok": proc.returncode == 0, "rc": proc.returncode,
                "platform": platform,
                "unit": "7 test_data goldens, bit-exact, cascade default",
                "tail": tail}

    return run_tool(
        "r5_conformance_tpu", "", [], timeout, out,
        argv0=[sys.executable, "-m", "chandy_lamport_tpu", "test",
               "--backend", "jax"],
        env=dict(os.environ, JAX_ENABLE_X64="1"), parse=parse)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--only", default="",
                   help="comma-separated step numbers (default: all)")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="bench-internal full-size attempt budget")
    p.add_argument("--out", default=os.path.join(ROOT, "BASELINE_MEASURED.jsonl"))
    p.add_argument("--no-resume", action="store_true",
                   help="re-run steps even if a banked TPU row exists")
    args = p.parse_args()
    only = {int(x) for x in args.only.split(",") if x} or set(range(1, 10))

    def banked(name: str, full: dict = None) -> bool:
        """A successful on-device row for this step already exists — skip
        it, so a plan re-fired after a mid-window wedge (probe_loop
        --rearm) spends the new window only on what's still missing.
        ``full`` pins asked-shape fields (e.g. batch): a clamped
        'tpu-small' fallback row must NOT bank the full-size step."""
        if args.no_resume or not os.path.exists(args.out):
            return False
        for line in open(args.out):
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if row.get("config") != name:
                continue
            if not (row.get("platform") == "tpu"
                    and (row.get("ok") is not False)):
                continue
            if full and any(row.get(k) != v for k, v in full.items()):
                continue
            log(f"--- {name}: banked on-device row exists, skipping")
            return True
        return False

    aborted = []

    def record(name, row):
        """Shared tunnel-loss detector: on any non-TPU outcome, re-probe.
        Tunnel gone -> stop the plan (the watchdog re-fires it, resume
        skips banked rows). Tunnel alive -> the failure is row-specific;
        keep going."""
        if row and row.get("platform") != "tpu" and not tunnel_alive():
            aborted.append(name)
        return row

    def bench(name, extra, timeout=None, full=None, rebank=False):
        """``rebank``: always re-run even if banked — for the auto-layout
        baseline, which must come from the SAME window as whatever A/B arm
        runs in it (cross-window spread is the ±3-5% confound the row
        exists to remove); with the persistent compile cache a re-run
        costs ~a minute."""
        if not rebank and banked(name, full):
            return {}
        if aborted:
            log(f"--- {name}: tunnel lost earlier in the plan, leaving "
                "queued for the next window")
            return {}
        t = timeout or args.timeout
        # --assume-tpu: this plan only fires on a live probe (probe_loop
        # or the operator), so skip each row's 40-120s probe ladder — the
        # observed tunnel windows are 5-9 minutes long and the probes were
        # costing a row per window. A wedge mid-plan now costs one
        # full-size worker timeout plus the cpu fallback row, after which
        # record()'s tunnel-loss detector aborts the plan.
        return record(name, run_tool(
            name, "bench.py", extra + ["--assume-tpu", "--timeout", str(t)],
            t * 3 + 600, args.out))

    HEADLINE = ["--graph", "sf", "--nodes", "1024", "--batch", "2048",
                "--phases", "32", "--snapshots", "8", "--scheduler", "sync"]

    if 1 in only and not banked("r5_conformance_tpu") and not aborted:
        record("r5_conformance_tpu", conformance(1800.0, args.out))
    # step 6 runs FIRST among benches: the "exact semantics >= 10M" row is
    # the twice-carried VERDICT item (#3) and the observed windows fit
    # ~2-5 rows — value order, not numeric order. The uint16 A/B (step 3)
    # moved BEHIND the exact rows on 2026-07-31: its fresh compile sat
    # >840s and the window died under it, so on a re-fire it would retry
    # first and risk eating every later window while the exact rows starve.
    if 6 in only:
        # ring-10 half FIRST (promoted from step 9 on 2026-07-31): a CPU
        # gauge of the ER-256 half measured 13.9k node-ticks/s at B=256 —
        # its marker density (4 snapshots x 763 edges -> ~40-80 cascade
        # iterations per tick) makes it the slow row, while ring-10's one
        # 10-edge snapshot leaves most ticks at zero iterations, so the
        # ring half is the one that clears the >=10M bar. Short budget: if
        # its warmup wedges the window again (it did once, 2026-07-30
        # 21:04, on pre-input-formats-fix code) the loss is bounded.
        bench("r5_exact_at_scale_ring10",
              ["--graph", "ring", "--nodes", "10", "--batch", "131072",
               "--phases", "32", "--snapshots", "1",
               "--scheduler", "exact", "--delay", "hash"],
              timeout=420.0, full={"batch": 131072})
        bench("r5_exact_at_scale_er256",
              ["--graph", "er", "--nodes", "256", "--batch", "4096",
               "--phases", "32", "--snapshots", "4",
               "--scheduler", "exact", "--delay", "hash"],
              timeout=600.0, full={"batch": 4096})
        # the wave formulation's headline A/B (same shape as the cascade
        # row above): 15.4x the cascade on a CPU gauge at this marker
        # density (747.6 -> 48.5 ms/batched tick at B=64, bit-identical
        # trajectories — tests/test_wave.py)
        bench("r5_exact_at_scale_er256_wave",
              ["--graph", "er", "--nodes", "256", "--batch", "4096",
               "--phases", "32", "--snapshots", "4", "--scheduler", "exact",
               "--exact-impl", "wave", "--delay", "hash"],
              timeout=600.0, full={"batch": 4096})
    if 4 in only:
        # single repeat: an exact row's value is existence + magnitude, not
        # best-of-3, and the cascade's sequential cost (~S*E handle_marker
        # steps per run, ~24.5k here) makes repeats expensive
        bench("r5_config4_sf1k_exact",
              ["--graph", "sf", "--nodes", "1024", "--batch", "2048",
               "--phases", "32", "--snapshots", "8", "--scheduler", "exact",
               "--repeats", "1"],
              full={"batch": 2048})
        # the N=8192 "no UNAVAILABLE" proof (VERDICT r4 #2): the round-3
        # fault was program-size/structure, which is batch- and S-
        # independent, so a reduced row (S=2 quarters the ~196k sequential
        # marker steps of the full ladder shape; B=8 shrinks every plane)
        # proves the device runs the N=8192 cascade clean within a short
        # window. The full ladder-shape row runs dead last (step 9) if the
        # window survives that long.
        bench("r5_config5_sf8k_exact_proof",
              ["--graph", "sf", "--nodes", "8192", "--batch", "8",
               "--phases", "8", "--snapshots", "2", "--scheduler", "exact",
               "--repeats", "1"],
              timeout=600.0, full={"batch": 8})
        # config-4 exact through the wave: the cascade's ~S*E sequential
        # marker steps collapse to per-destination conflict depth
        bench("r5_config4_sf1k_exact_wave",
              ["--graph", "sf", "--nodes", "1024", "--batch", "2048",
               "--phases", "32", "--snapshots", "8", "--scheduler", "exact",
               "--exact-impl", "wave", "--repeats", "1"],
              timeout=600.0, full={"batch": 2048})
    if 5 in only:
        bench("r5_config2_ring10_sync",
              ["--graph", "ring", "--nodes", "10", "--batch", "131072",
               "--phases", "32", "--snapshots", "1", "--scheduler", "sync"],
              full={"batch": 131072})
    if 2 in only:
        bench("r5_config4_sf1k_sync_rowmajor",
              HEADLINE + ["--layouts", "default"], full={"batch": 2048})
    if 3 in only and not banked("r5_config4_sf1k_sync_win16",
                                full={"batch": 2048}) and not aborted:
        # same-window auto-layout baseline: window-to-window spread on the
        # shared tunnel was ±3-5% in rounds 3/5, so the A/B pair compares
        # against THIS window's auto row, not window 1's 120.5M. rebank:
        # re-runs whenever the uint16 arm is still unbanked, so the pair
        # is never split across windows (rows carry ts for pairing).
        bench("r5_config4_sf1k_sync_auto",
              HEADLINE, full={"batch": 2048}, rebank=True)
        # 600s, not 900: its one observed on-device compile outlived the
        # window (>840s); past ~10 min the window is dead anyway, and a
        # shorter worker lets the plan detect tunnel loss sooner.
        bench("r5_config4_sf1k_sync_win16",
              HEADLINE + ["--window-dtype", "uint16"],
              timeout=600.0, full={"batch": 2048})
    if 7 in only:
        bench("r5_gshard_base_sf1k_b1",
              ["--graph", "sf", "--nodes", "1024", "--batch", "1",
               "--phases", "32", "--snapshots", "8", "--scheduler", "sync"],
              full={"batch": 1})
        bench("r5_gshard_1shard_sf1k",
              ["--graph", "sf", "--nodes", "1024", "--graphshard", "1",
               "--phases", "32", "--snapshots", "8"])
    if 8 in only:
        for preset in ("northstar", "config3", "config4"):
            if banked(f"r5_maxbatch_{preset}") or aborted:
                continue
            record(f"r5_maxbatch_{preset}", run_tool(
                f"r5_maxbatch_{preset}", "tools/maxbatch.py",
                ["--preset", preset, "--record-dtype", "int16"],
                3600.0, args.out))
    if 9 in only:
        # the literal north-star shape under BIT-EXACT reference
        # semantics: ring-10 x 1M lanes, cascade (at ring's in-degree 1
        # the wave's per-tick precompute outweighs its parallelism — a
        # CPU A/B at B=1024 measured cascade 4.30 vs wave 9.11 ms/tick).
        # Step 9 because a 1M-lane exact warmup is the known wedge-risk
        # shape (the B=131k variant wedged window 1 on pre-fix code)
        bench("r5_northstar_exact",
              ["--graph", "ring", "--nodes", "10", "--batch", "1048576",
               "--phases", "32", "--snapshots", "1", "--scheduler", "exact",
               "--delay", "hash", "--repeats", "1"],
              timeout=600.0, full={"batch": 1048576})
        # the full ladder-shape config-5 exact rows. The wave form first:
        # its sequential depth is per-destination conflict count (~in-
        # degree 3), not the cascade's ~196k total marker steps, so it is
        # the one that can realistically finish inside a window
        bench("r5_config5_sf8k_exact_full_wave",
              ["--graph", "sf", "--nodes", "8192", "--batch", "512",
               "--phases", "16", "--snapshots", "8", "--scheduler", "exact",
               "--exact-impl", "wave", "--repeats", "1"],
              timeout=900.0, full={"batch": 512})
        # the cascade full row, dead last: likely longer than a whole
        # tunnel window, so it must never queue ahead of anything
        bench("r5_config5_sf8k_exact_full",
              ["--graph", "sf", "--nodes", "8192", "--batch", "512",
               "--phases", "16", "--snapshots", "8", "--scheduler", "exact",
               "--repeats", "1"],
              timeout=1500.0, full={"batch": 512})
    if aborted:
        log(f"plan aborted at '{aborted[0]}' (tunnel lost); re-fire to "
            "resume the remaining rows")
        sys.exit(3)
    log("r5 measurement plan complete")


if __name__ == "__main__":
    main()
