#!/usr/bin/env python
"""Summarize the round-5 measured rows for BASELINE.md.

Reads ``BASELINE_MEASURED.jsonl``, keeps the LAST row per r5_* config
(the plan appends retries), and prints a markdown table plus A/B deltas
(layouts, window dtype, exact-vs-sync) computed against the same-window
auto baseline when it exists. Pure bookkeeping — the authoritative rows
stay in the jsonl; what they measure is the reference hot loop,
/root/reference/chandy_lamport/sim.go:71-95.

Usage: python tools/r5_report.py [--jsonl PATH]
"""

from __future__ import annotations

import argparse
import json
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# config -> short label for the table
LABELS = {
    "r5_conformance_tpu": "7/7 goldens bit-exact (cascade, x64)",
    "r5_config4_sf1k_sync_rowmajor": "4: SF-1k sync, row-major layouts",
    "r5_config4_sf1k_sync_auto": "4: SF-1k sync, auto layouts",
    "r5_config4_sf1k_sync_win16": "4: SF-1k sync, uint16 windows",
    "r5_exact_at_scale_er256": "3: ER-256 exact, cascade (hash delay)",
    "r5_exact_at_scale_er256_wave": "3: ER-256 exact, wave (hash delay)",
    "r5_config4_sf1k_exact": "4: SF-1k exact, cascade",
    "r5_config4_sf1k_exact_wave": "4: SF-1k exact, wave",
    "r5_config5_sf8k_exact_proof": "5: SF-8k exact proof (S=2, B=8)",
    "r5_config5_sf8k_exact_full_wave": "5: SF-8k exact full shape, wave",
    "r5_config5_sf8k_exact_full": "5: SF-8k exact full shape, cascade",
    "r5_northstar_exact": "north star, BIT-EXACT cascade (ring-10 x 1M)",
    "r5_config2_ring10_sync": "2: ring-10 sync B=131k",
    "r5_exact_at_scale_ring10": "2: ring-10 exact B=131k",
    "r5_gshard_base_sf1k_b1": "gshard baseline: SF-1k B=1 unsharded",
    "r5_gshard_1shard_sf1k": "gshard: SF-1k 1-shard formulation",
    "r5_maxbatch_northstar": "maxbatch: north-star ring-10",
    "r5_maxbatch_config3": "maxbatch: config 3",
    "r5_maxbatch_config4": "maxbatch: config 4",
}


def fmt(v):
    return f"{v / 1e6:.1f}M" if isinstance(v, (int, float)) and v > 1e4 else v


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--jsonl",
                   default=os.path.join(ROOT, "BASELINE_MEASURED.jsonl"))
    args = p.parse_args()

    rows = {}
    for line in open(args.jsonl):
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        cfg = row.get("config", "")
        if cfg.startswith("r5_"):
            rows[cfg] = row  # last wins

    print("| Row | platform | value | unit | notes |")
    print("|---|---|---|---|---|")
    for cfg, label in LABELS.items():
        row = rows.get(cfg)
        if row is None:
            print(f"| {label} | — | *not yet banked* | | |")
            continue
        plat = row.get("platform", "?")
        val = row.get("value", row.get("ok"))
        unit = row.get("unit", "")
        notes = []
        if row.get("ts"):
            notes.append(row["ts"][5:16])  # MM-DDTHH:MM — window pairing
        if row.get("error"):
            notes.append(str(row["error"])[:60])
        if row.get("vs_baseline") is not None:
            notes.append(f"{row['vs_baseline']}x target")
        if row.get("layouts"):
            notes.append(f"layouts={row['layouts']}")
        if row.get("batch") is not None:
            notes.append(f"B={row['batch']}")
        print(f"| {label} | {plat} | {fmt(val)} | {unit} | "
              f"{'; '.join(notes)} |")
    extra = sorted(set(rows) - set(LABELS))
    for cfg in extra:
        row = rows[cfg]
        print(f"| {cfg} | {row.get('platform', '?')} | "
              f"{fmt(row.get('value'))} | {row.get('unit', '')} | |")

    base = rows.get("r5_config4_sf1k_sync_auto")
    if base and base.get("platform") == "tpu":
        b = base["value"]
        print("\nA/B vs same-window auto baseline "
              f"({fmt(b)} node-ticks/s):")
        for cfg, tag in (("r5_config4_sf1k_sync_rowmajor", "row-major"),
                         ("r5_config4_sf1k_sync_win16", "uint16 windows"),
                         ("r5_config4_sf1k_exact", "exact scheduler")):
            row = rows.get(cfg)
            if row and row.get("platform") == "tpu":
                d = (row["value"] - b) / b * 100
                print(f"  {tag}: {fmt(row['value'])} ({d:+.1f}%)")


if __name__ == "__main__":
    main()
