#!/usr/bin/env python
"""Randomized oracle-differential soak for the sync scheduler.

CI's differential suites (tests/test_sync_differential.py,
tests/test_bf16_and_capacity.py) run a handful of fixed seeds; this tool
drives an arbitrary number of random (graph, program, delay) combinations
through the dense sync kernel and the independent SyncOracle and demands
exact agreement on balances, time, and every snapshot's per-edge recorded
window — the deep-confidence battery for representation changes (window
log, merge keys, split markers). Each case also runs the in-run
conservation sanitizer (check_every).

Usage: python tools/soak.py [--cases N] [--seed-base S]
Prints one JSON line; exit 1 on any mismatch.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--cases", type=int, default=24)
    p.add_argument("--seed-base", type=int, default=9000)
    args = p.parse_args()

    import jax

    # the env var alone cannot override this image's TPU plugin; a soak is
    # CPU work and must not hang when the device tunnel is down
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.core.state import DenseTopology, recorded_window
    from chandy_lamport_tpu.core.syncsim import SyncOracle
    from chandy_lamport_tpu.models.delay import FixedDelay
    from chandy_lamport_tpu.models.workloads import erdos_renyi, scale_free
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    t0 = time.perf_counter()
    fails = []
    for case in range(args.cases):
        rng = random.Random(args.seed_base + case)
        n = rng.randrange(4, 20)
        gseed = args.seed_base + case  # graphs vary with --seed-base too
        spec = (scale_free(n, 2, seed=gseed, tokens=80) if case % 2
                else erdos_renyi(max(n, 5), 2.5, seed=gseed, tokens=80))
        topo = DenseTopology(spec)
        delay = rng.randrange(1, 5)
        phases = rng.randrange(5, 14)
        amounts = np.zeros((phases, topo.e), np.int32)
        floor = topo.tokens0.astype(np.int64).copy()
        for ph in range(phases):
            for e in rng.sample(range(topo.e), k=max(1, topo.e // 2)):
                src = int(topo.edge_src[e])
                if floor[src] >= 2:
                    amounts[ph, e] += 1
                    floor[src] -= 1
        n_snaps = rng.randrange(1, 4)
        snap = np.full((phases, n_snaps), -1, np.int32)
        for j in range(n_snaps):
            snap[rng.randrange(phases), j] = rng.randrange(topo.n)

        runner = BatchedRunner(
            spec, SimConfig(queue_capacity=32, max_recorded=128,
                            max_snapshots=8),
            FixedJaxDelay(delay), batch=1, scheduler="sync", check_every=3)
        final = jax.device_get(
            runner.run_storm(runner.init_batch(), (amounts, snap)))
        lane = jax.tree_util.tree_map(lambda x: x[0], final)

        oracle = SyncOracle(topo, FixedDelay(delay))
        for ph in range(phases):
            oracle.bulk_send([int(a) for a in amounts[ph]])
            nodes = [int(x) for x in snap[ph] if x >= 0]
            if nodes:
                oracle.start_snapshots(nodes)
            oracle.tick()
        oracle.drain_and_flush()

        ok = (int(lane.error) == 0
              and oracle.tokens == [int(t) for t in lane.tokens]
              and oracle.time == int(lane.time))
        if ok:
            for sid in range(int(lane.next_sid)):
                for e in range(topo.e):
                    if (oracle.recorded[sid].get(e, [])
                            != recorded_window(lane, sid, e)):
                        ok = False
        print(f"case {case}: {'ok' if ok else 'MISMATCH'} "
              f"(n={topo.n} e={topo.e} delay={delay} phases={phases})",
              file=sys.stderr, flush=True)
        if not ok:
            fails.append(case)

    print(json.dumps({
        "metric": "soak_oracle_match",
        "cases": args.cases,
        "matched": args.cases - len(fails),
        "failed_cases": fails,
        "seconds": round(time.perf_counter() - t0, 1),
    }))
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
