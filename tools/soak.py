#!/usr/bin/env python
"""Randomized oracle-differential soak battery for ALL THREE engines.

CI's differential suites run a handful of fixed seeds; this tool drives an
arbitrary number of random (graph, program, delay) combinations through each
engine against its independent oracle and demands exact agreement — the
deep-confidence battery for representation changes (window log, merge keys,
split markers, the cascade tick). The invariant source is the reference's
checkTokens + assertEqual (test_common.go:222-328); the comparisons here are
stronger (exact per-edge windows / exact message order).

Engines (--engine, default "all"):
  sync   dense sync kernel (ops/tick._sync_tick) vs the independent
         SyncOracle (core/syncsim), fixed delays, window-level comparison,
         with the in-run conservation sanitizer on (check_every).
  exact  dense bit-exact kernel (the cascade tick) vs the pure-Python
         parity backend (core/parity) on random event scripts, alternating
         GoExact and Fixed delay models — exact snapshot and message-order
         equality plus final balances.
  shard  graph-sharded sync runner (parallel/graphshard) vs the unsharded
         dense sync kernel at random shard counts on the forced CPU mesh —
         bit-equality of balances, frozen maps, completion, and every
         per-(snapshot, edge) recorded window after undoing the shard
         edge partition.

Usage: python tools/soak.py [--engine E] [--cases N] [--seed-base S]
Prints one JSON line; exit 1 on any mismatch.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _err_suffix(bits: int) -> str:
    """Decoded ERR_* names for a nonzero bitmask — raw ints never reach the
    log (core/state.decode_error_bits)."""
    if not bits:
        return ""
    from chandy_lamport_tpu.core.state import decode_error_bits

    return f" errors={decode_error_bits(bits)}"


def _random_storm(rng, topo, phases, n_snaps_max):
    import numpy as np

    amounts = np.zeros((phases, topo.e), np.int32)
    floor = topo.tokens0.astype(np.int64).copy()
    for ph in range(phases):
        for e in rng.sample(range(topo.e), k=max(1, topo.e // 2)):
            src = int(topo.edge_src[e])
            if floor[src] >= 2:
                amounts[ph, e] += 1
                floor[src] -= 1
    n_snaps = rng.randrange(1, n_snaps_max)
    snap = np.full((phases, n_snaps), -1, np.int32)
    for j in range(n_snaps):
        snap[rng.randrange(phases), j] = rng.randrange(topo.n)
    return amounts, snap


def soak_sync(case: int, seed_base: int):
    import jax
    import numpy as np

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.core.state import DenseTopology, recorded_window
    from chandy_lamport_tpu.core.syncsim import SyncOracle
    from chandy_lamport_tpu.models.delay import FixedDelay
    from chandy_lamport_tpu.models.workloads import erdos_renyi, scale_free
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    rng = random.Random(seed_base + case)
    n = rng.randrange(4, 20)
    gseed = seed_base + case  # graphs vary with --seed-base too
    spec = (scale_free(n, 2, seed=gseed, tokens=80) if case % 2
            else erdos_renyi(max(n, 5), 2.5, seed=gseed, tokens=80))
    topo = DenseTopology(spec)
    delay = rng.randrange(1, 5)
    phases = rng.randrange(5, 14)
    amounts, snap = _random_storm(rng, topo, phases, 4)

    wd = rng.choice(["int32", "uint16"])
    runner = BatchedRunner(
        spec, SimConfig(queue_capacity=32, max_recorded=128, max_snapshots=8,
                        window_dtype=wd),
        FixedJaxDelay(delay), batch=1, scheduler="sync", check_every=3)
    final = jax.device_get(
        runner.run_storm(runner.init_batch(), (amounts, snap)))
    lane = jax.tree_util.tree_map(lambda x: x[0], final)

    oracle = SyncOracle(topo, FixedDelay(delay))
    for ph in range(phases):
        oracle.bulk_send([int(a) for a in amounts[ph]])
        nodes = [int(x) for x in snap[ph] if x >= 0]
        if nodes:
            oracle.start_snapshots(nodes)
        oracle.tick()
    oracle.drain_and_flush()

    ok = (int(lane.error) == 0
          and oracle.tokens == [int(t) for t in lane.tokens]
          and oracle.time == int(lane.time))
    if ok:
        for sid in range(int(lane.next_sid)):
            for e in range(topo.e):
                if (oracle.recorded[sid].get(e, [])
                        != recorded_window(lane, sid, e)):
                    ok = False
    log(f"sync case {case}: {'ok' if ok else 'MISMATCH'} "
        f"(n={topo.n} e={topo.e} delay={delay} phases={phases} win={wd})"
        + _err_suffix(int(lane.error)))
    return ok, wd


def soak_exact(case: int, seed_base: int):
    from chandy_lamport_tpu.api import run_events
    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.delay import FixedDelay, GoExactDelay
    from chandy_lamport_tpu.utils.randgen import (
        random_script,
        random_strongly_connected,
    )

    rng = random.Random(seed_base + 50_000 + case)
    topo = random_strongly_connected(rng, rng.randrange(3, 14))
    events = random_script(rng, topo, rng.randrange(10, 50))
    cfg = SimConfig(queue_capacity=64, max_recorded=128,
                    window_dtype=rng.choice(["int32", "uint16"]))
    # alternate the two delay models the exact scheduler must serve: the
    # draw-order-sensitive Go stream and the stateless fixed model. Fixed
    # cases also randomize the tick formulation — the wave form only
    # serves position-addressable samplers, so it enters the battery here
    mk_delay = ((lambda: GoExactDelay(seed_base + case)) if case % 2
                else (lambda: FixedDelay(1 + case % 5)))
    impl = "cascade" if case % 2 else rng.choice(["cascade", "wave"])

    p_snaps, p_sim = run_events("parity", topo, events, mk_delay())
    d_snaps, d_sim = run_events("jax", topo, events, mk_delay(), cfg,
                                exact_impl=impl)

    ok = (p_sim.node_tokens() == d_sim.node_tokens()
          and p_sim.total_tokens() == d_sim.total_tokens()
          and len(p_snaps) == len(d_snaps))
    if ok:
        for ps, ds in zip(p_snaps, d_snaps):
            if not (ps.id == ds.id and ps.token_map == ds.token_map
                    and ps.messages == ds.messages):
                ok = False
    log(f"exact case {case}: {'ok' if ok else 'MISMATCH'} "
        f"(n={len(topo.nodes)} events={len(events)} "
        f"delay={'go' if case % 2 else 'fixed'} impl={impl} "
        f"win={cfg.window_dtype})")
    return ok, cfg.window_dtype


def soak_shard(case: int, seed_base: int):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.core.state import recorded_window
    from chandy_lamport_tpu.models.workloads import erdos_renyi
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner

    rng = random.Random(seed_base + 90_000 + case)
    shards = rng.choice([s for s in (1, 2, 4, 8)
                         if s <= len(jax.devices())][1:] or [1])
    nl = rng.randrange(2, 6)           # nodes per shard
    n = shards * nl
    spec = erdos_renyi(n, 2.5, seed=seed_base + case, tokens=80)
    cfg = SimConfig(queue_capacity=32, max_snapshots=8, max_recorded=64,
                    window_dtype=rng.choice(["int32", "uint16"]))
    delay = rng.randrange(1, 5)
    phases = rng.randrange(5, 14)

    ref = BatchedRunner(spec, cfg, FixedJaxDelay(delay), batch=1,
                        scheduler="sync")
    amounts, snap = _random_storm(rng, ref.topo, phases, 4)
    ref_final = jax.tree_util.tree_map(
        lambda x: np.asarray(x)[0],
        jax.device_get(ref.run_storm(ref.init_batch(), (amounts, snap))))

    mesh = Mesh(np.array(jax.devices()[:shards]), ("graph",))
    gs = GraphShardedRunner(spec, cfg, mesh, fixed_delay=delay)
    final = jax.device_get(gs.run_storm(gs.init_state(), amounts, snap))

    ok = (int(final.error) == 0 == int(ref_final.error)
          and int(final.time) == int(ref_final.time)
          and np.array_equal(final.tokens.reshape(-1), ref_final.tokens)
          and np.array_equal(final.completed, ref_final.completed))
    if ok:
        # undo the shard edge partition, then compare every recorded window
        shard_of = gs.topo.edge_src // gs.nl
        counts = [int((shard_of == p).sum()) for p in range(shards)]
        perm = [i for p in range(shards)
                for i in range(gs.topo.e) if shard_of[i] == p]
        frozen = np.concatenate(
            [final.frozen[p] for p in range(shards)], axis=-1)
        ok = np.array_equal(frozen, ref_final.frozen)
        from types import SimpleNamespace

        for sid in range(int(ref_final.next_sid)):
            if not ok:
                break
            gi = 0
            for p in range(shards):
                shard = SimpleNamespace(
                    log_amt=final.log_amt[p], rec_cnt=final.rec_cnt[p],
                    rec_start=final.rec_start[p], rec_end=final.rec_end[p],
                    recording=final.recording[p])
                for el in range(counts[p]):
                    if (recorded_window(shard, sid, el)
                            != recorded_window(ref_final, sid, perm[gi])):
                        ok = False
                    gi += 1
    log(f"shard case {case}: {'ok' if ok else 'MISMATCH'} "
        f"(n={n} shards={shards} delay={delay} phases={phases} "
        f"win={cfg.window_dtype})"
        + _err_suffix(int(final.error) | int(ref_final.error)))
    return ok, cfg.window_dtype


ENGINES = {"sync": soak_sync, "exact": soak_exact, "shard": soak_shard}


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--engine", choices=[*ENGINES, "all"], default="all")
    p.add_argument("--cases", type=int, default=12,
                   help="cases per engine")
    p.add_argument("--seed-base", type=int, default=9000)
    args = p.parse_args(argv)

    # the shard engine needs a multi-device mesh; harmless if jax is already
    # initialized (then the caller — e.g. the pytest conftest — set it)
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    # the env var alone cannot override this image's TPU plugin; a soak is
    # CPU work and must not hang when the device tunnel is down
    jax.config.update("jax_platforms", "cpu")
    # the exact engine's GoExact stream needs 64-bit ints under jit
    jax.config.update("jax_enable_x64", True)

    engines = list(ENGINES) if args.engine == "all" else [args.engine]
    t0 = time.perf_counter()
    fails = []
    dtypes = {"int32": 0, "uint16": 0}
    for engine in engines:
        for case in range(args.cases):
            ok, wd = ENGINES[engine](case, args.seed_base)
            dtypes[wd] += 1
            if not ok:
                fails.append(f"{engine}:{case}")

    print(json.dumps({
        "metric": "soak_oracle_match",
        "engines": engines,
        "cases_per_engine": args.cases,
        "matched": len(engines) * args.cases - len(fails),
        "failed_cases": fails,
        # evidence that the randomized battery exercised BOTH window-plane
        # dtypes (VERDICT r4 #7), not which cases failed under which
        "window_dtypes": dtypes,
        "seconds": round(time.perf_counter() - t0, 1),
    }))
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
