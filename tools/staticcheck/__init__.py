"""clsim-guard: the simulator's static + runtime analysis planes.

Plane 1 (``jaxpr_audit``) traces every public jitted entry point across the
engine-knob matrix (``chandy_lamport_tpu.config.ENGINE_KNOBS`` x
exact_impl x scheduler x faults x trace) with ``jax.make_jaxpr`` and audits
the traces themselves: dtype discipline, constant-capture budget, donation,
host-callback leaks, collective well-formedness, and a lowering-fingerprint
registry (``fingerprints.json``) that fails when a trace changes without
being regenerated.

Plane 2 (``ast_lint``) runs custom AST rules over the package source:
error-bit registry coverage, checkpoint-format single-sourcing, the
engine-knob pattern (resolver + CLI flag + bench row per knob),
traced-module purity (no ``time``/``random``/``np.random``), explicit
``mode=`` on sharded-plane scatters, no host syncs in device-loop
packages, and locked ``os.replace`` commits of shared cache files.

Plane 3 (``hlo_cost``) backend-compiles the same entry-arm matrix and
checks a static cost row per arm (FLOPs, HBM bytes, collective
count/bytes, scatter/gather/fusion counts, peak live buffers) against
schema-versioned ceilings in ``cost_budgets.json``.

Plane 4 (``runtime_sentry``) actually dispatches tiny shapes per engine
knob row under ``utils/guards.RuntimeGuards`` and asserts zero retraces
and zero un-allowlisted transfers per steady-state step after warmup.

Run ``python -m tools.staticcheck`` from the repo root; it writes a JSON
violations report and exits nonzero on any non-allowlisted violation.
Intentional exceptions live in ``allowlist.py`` with one-line reasons.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule failure: ``rule`` is the stable rule id, ``where`` locates it
    (``path:line`` for AST rules, the entry key for jaxpr rules), ``detail``
    says what was found and what the rule wanted instead."""

    rule: str
    where: str
    detail: str

    def key(self) -> str:
        return f"{self.rule}@{self.where}"

    def to_dict(self) -> Dict[str, str]:
        return dataclasses.asdict(self)


def apply_allowlist(violations: Sequence[Violation]):
    """Split ``violations`` into (kept, allowed) against ``allowlist.ALLOW``.

    A violation is allowed when some entry's rule matches exactly and its
    ``where`` pattern fnmatches the violation's ``where``. Allowed
    violations still appear in the report (with their reasons) so the
    allowlist is auditable, but do not affect the exit code.
    """
    from tools.staticcheck.allowlist import ALLOW

    kept: List[Violation] = []
    allowed: List[dict] = []
    for v in violations:
        reason: Optional[str] = None
        for a in ALLOW:
            if a.rule == v.rule and fnmatch.fnmatch(v.where, a.where):
                reason = a.reason
                break
        if reason is None:
            kept.append(v)
        else:
            allowed.append({**v.to_dict(), "allowed_because": reason})
    return kept, allowed


def build_report(violations: Sequence[Violation], allowed: Sequence[dict],
                 *, entries_audited: Sequence[str] = (),
                 mode: str = "full", notes: Sequence[str] = ()) -> dict:
    """Assemble the JSON report ``__main__``/``cli audit`` emit."""
    report = {
        "tool": "clsim-staticcheck",
        "mode": mode,
        "entries_audited": list(entries_audited),
        "num_violations": len(violations),
        "violations": [v.to_dict() for v in violations],
        "allowed": list(allowed),
        "clean": not violations,
    }
    if notes:
        report["notes"] = list(notes)
    return report


def report_to_json(report: dict) -> str:
    return json.dumps(report, indent=2, sort_keys=False)
