"""``python -m tools.staticcheck`` — run both analysis planes, write a JSON
violations report, exit nonzero on any non-allowlisted violation.

The jaxpr plane needs the canonical audit environment (CPU backend, 8 host
devices, x64) pinned BEFORE jax is imported, so this module sets it up
first thing — same contract as tests/conftest.py and cli.py, which is what
keeps the fingerprint registry agreeing between the CLI and the suite.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # repo root on sys.path so `python tools/staticcheck/__main__.py` works
    # too (the -m form from the repo root needs nothing)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)

    from tools.staticcheck import (
        apply_allowlist,
        build_report,
        report_to_json,
    )
    from tools.staticcheck import ast_lint, jaxpr_audit

    ap = argparse.ArgumentParser(
        prog="tools.staticcheck",
        description="clsim-audit: jaxpr trace auditor + AST lint")
    ap.add_argument("--plane", choices=("jaxpr", "ast", "both"),
                    default="both")
    ap.add_argument("--fast", action="store_true",
                    help="jaxpr plane: one arm per engine axis instead of "
                         "the full knob matrix")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the report here (default: stdout only)")
    ap.add_argument("--fingerprints-update", action="store_true",
                    help="re-register lowering fingerprints for every "
                         "entry traced in this run")
    ap.add_argument("--no-fingerprints", action="store_true",
                    help="skip the fingerprint registry check")
    args = ap.parse_args(argv)

    # only the jaxpr plane needs jax (and the pinned audit env) at all —
    # a lint-only run must stay import-light and never mutate XLA env vars
    if args.plane in ("jaxpr", "both"):
        jaxpr_audit.ensure_env()

    violations = []
    audited = []
    notes = []
    mode = "fast" if args.fast else "full"
    if args.plane in ("ast", "both"):
        violations.extend(ast_lint.lint_tree(root))
    if args.plane in ("jaxpr", "both"):
        vs, keys, _fps = jaxpr_audit.audit(
            mode,
            check_fingerprints=not args.no_fingerprints,
            update_fingerprints=args.fingerprints_update)
        violations.extend(vs)
        audited.extend(keys)
        if jaxpr_audit._LAST_REGISTRY_NOTE:
            notes.append(jaxpr_audit._LAST_REGISTRY_NOTE)

    kept, allowed = apply_allowlist(violations)
    report = build_report(kept, allowed, entries_audited=audited, mode=mode,
                          notes=notes)
    text = report_to_json(report)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    print(text)
    if kept:
        print(f"staticcheck: {len(kept)} violation(s)", file=sys.stderr)
        return 1
    print("staticcheck: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
