"""``python -m tools.staticcheck`` — run the analysis planes, write a JSON
violations report, exit nonzero on any non-allowlisted violation.

Planes: ``jaxpr`` (trace structure), ``ast`` (source lint), ``cost``
(HLO cost budgets), ``runtime`` (the guard sentry, actually dispatches
tiny shapes). ``--plane all`` (the default) runs everything; ``--plane
both`` keeps the historical jaxpr+ast pairing for quick structural runs.

The jax-touching planes need the canonical audit environment (CPU
backend, 8 host devices, x64) pinned BEFORE jax is imported, so this
module sets it up first thing — same contract as tests/conftest.py and
cli.py, which is what keeps the fingerprint registry agreeing between
the CLI and the suite.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # repo root on sys.path so `python tools/staticcheck/__main__.py` works
    # too (the -m form from the repo root needs nothing)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)

    from tools.staticcheck import (
        apply_allowlist,
        build_report,
        report_to_json,
    )
    from tools.staticcheck import ast_lint, hlo_cost, jaxpr_audit, \
        runtime_sentry

    ap = argparse.ArgumentParser(
        prog="tools.staticcheck",
        description="clsim-audit: jaxpr/AST/cost/runtime analysis planes")
    ap.add_argument("--plane",
                    choices=("jaxpr", "ast", "cost", "runtime", "both",
                             "all"),
                    default="all",
                    help="'both' = jaxpr+ast (the historical pair); "
                         "'all' adds the cost-budget and runtime-sentry "
                         "planes (default)")
    ap.add_argument("--fast", action="store_true",
                    help="jaxpr/cost planes: one arm per engine axis "
                         "instead of the full knob matrix; runtime plane: "
                         "one row per loop family")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the report here (default: stdout only)")
    ap.add_argument("--fingerprints-update", action="store_true",
                    help="re-register lowering fingerprints for every "
                         "entry traced in this run")
    ap.add_argument("--no-fingerprints", action="store_true",
                    help="skip the fingerprint registry check")
    ap.add_argument("--budgets-update", action="store_true",
                    help="re-pin cost_budgets.json for every arm measured "
                         "in this run")
    ap.add_argument("--no-budgets", action="store_true",
                    help="measure the cost plane but skip the budget "
                         "comparison")
    ap.add_argument("--regen-registries", action="store_true",
                    help="regenerate BOTH registries — lowering "
                         "fingerprints AND cost budgets — in one run "
                         "(forces the jaxpr+cost planes on top of "
                         "--plane, implies --fingerprints-update and "
                         "--budgets-update). The one command a PR that "
                         "intentionally changes a lowering or a cost "
                         "ceiling needs; prints a loud reminder when "
                         "either registry was recorded under a different "
                         "jax version than the running one")
    args = ap.parse_args(argv)

    if args.regen_registries:
        args.fingerprints_update = True
        args.budgets_update = True

    planes = {
        "jaxpr": ("jaxpr",),
        "ast": ("ast",),
        "cost": ("cost",),
        "runtime": ("runtime",),
        "both": ("jaxpr", "ast"),
        "all": ("jaxpr", "ast", "cost", "runtime"),
    }[args.plane]
    if args.regen_registries:
        # both registries regenerate from the same process so their
        # recorded jax versions can never drift apart
        planes = tuple(dict.fromkeys(planes + ("jaxpr", "cost")))

    # only the jax-touching planes need jax (and the pinned audit env) at
    # all — a lint-only run must stay import-light and never mutate XLA
    # env vars
    if set(planes) & {"jaxpr", "cost", "runtime"}:
        jaxpr_audit.ensure_env()

    if args.regen_registries:
        # loud stale-version reminder BEFORE regenerating: a registry
        # recorded under another jax is about to be re-pinned under this
        # one, which rebinds the comparison gate to this toolchain
        import jax
        for label, loader in (("fingerprints.json",
                               jaxpr_audit.load_registry),
                              ("cost_budgets.json", hlo_cost.load_budgets)):
            try:
                _, recorded = loader()
            except ValueError:
                recorded = None
            if recorded is not None and recorded != jax.__version__:
                print(f"staticcheck: REMINDER — {label} was recorded "
                      f"under jax {recorded}; regenerating under jax "
                      f"{jax.__version__} re-pins every gate to this "
                      f"toolchain", file=sys.stderr)

    violations = []
    audited = []
    notes = []
    mode = "fast" if args.fast else "full"
    if "ast" in planes:
        violations.extend(ast_lint.lint_tree(root))
    if "jaxpr" in planes:
        vs, keys, _fps = jaxpr_audit.audit(
            mode,
            check_fingerprints=not args.no_fingerprints,
            update_fingerprints=args.fingerprints_update)
        violations.extend(vs)
        audited.extend(keys)
        if jaxpr_audit._LAST_REGISTRY_NOTE:
            notes.append(jaxpr_audit._LAST_REGISTRY_NOTE)
    if "cost" in planes:
        vs, keys, _rows = hlo_cost.audit(
            mode,
            check_budgets=not args.no_budgets,
            update_budgets=args.budgets_update)
        violations.extend(vs)
        audited.extend(f"cost:{k}" for k in keys)
        if hlo_cost._LAST_BUDGET_NOTE:
            notes.append(hlo_cost._LAST_BUDGET_NOTE)
    if "runtime" in planes:
        vs, keys, _steps = runtime_sentry.audit(mode)
        violations.extend(vs)
        audited.extend(f"runtime:{k}" for k in keys)

    kept, allowed = apply_allowlist(violations)
    report = build_report(kept, allowed, entries_audited=audited, mode=mode,
                          notes=notes)
    text = report_to_json(report)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    print(text)
    if kept:
        print(f"staticcheck: {len(kept)} violation(s)", file=sys.stderr)
        return 1
    print("staticcheck: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
