"""Intentional exceptions to staticcheck rules, each with a one-line reason.

An entry suppresses violations whose ``rule`` matches exactly and whose
``where`` matches the fnmatch pattern. Allowed violations still show up in
the JSON report under ``allowed`` (with the reason), so every suppression
stays auditable; they just don't fail the run. Keep this list short — a
grown allowlist is the rule set rotting.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class Allow:
    rule: str
    where: str  # fnmatch pattern against Violation.where
    reason: str


ALLOW: Tuple[Allow, ...] = (
    Allow(
        rule="ckpt-version-literal",
        where="tests/test_recovery.py:*",
        reason="deliberately stale version via monkeypatch to prove the "
               "unsupported-version error path",
    ),
    Allow(
        rule="ckpt-version-literal",
        where="tests/test_stream.py:*",
        reason="deliberately bogus version via monkeypatch to prove "
               "load-time rejection of future checkpoints",
    ),
)
