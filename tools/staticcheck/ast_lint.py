"""Plane 2: custom AST rules over the package (and test) sources.

Every rule takes ``sources: {repo-relative path: source text}`` and returns
a list of Violations, so tests can feed synthetic violating sources without
touching the tree. ``lint_tree(root)`` loads the real files and runs the
whole rule set.

Rules (ids are stable; the README rule table documents them):

  err-bit-registry    ERR_* constants in core/state.py are distinct powers
                      of two with no gaps, and every one has exactly one
                      ERROR_REGISTRY decode row (and vice versa);
                      NUM_ERROR_BITS is ``len(ERROR_REGISTRY)``, not a
                      second literal.
  por-width           graphshard's ``_por`` error-plane reduction derives
                      its bit width from NUM_ERROR_BITS — a hardcoded
                      ``arange(<int>)`` silently drops newly added bits.
  ckpt-version-literal  checkpoint format version literals live ONLY in the
                      core/state.py history table; any other assignment or
                      monkeypatch.setattr of a ``*FORMAT_VERSION*`` name to
                      an int literal is flagged (test sites that prove the
                      rejection paths are allowlisted).
  ckpt-history        CHECKPOINT_FORMAT_HISTORY rows are consecutive
                      versions from 1 and CHECKPOINT_FORMAT_VERSION is
                      bound to the last row, not re-stated.
  knob-pattern        every ENGINE_KNOBS knob has a ``resolve_<knob>``
                      function somewhere in the package, a ``--<knob>`` CLI
                      flag (cli.py or bench.py), and a bench worker-row
                      field; SimConfig.__post_init__ validates against the
                      table rather than inline tuples.
  traced-import       modules whose code runs under jit must not import
                      ``time``/``random`` or touch ``np.random`` — host
                      RNG/clock in a traced file is either dead weight or a
                      nondeterminism bug waiting to be traced in.
  scatter-mode        ``.at[...].add/.set/...`` on the sharded planes in
                      parallel/graphshard.py must pass an explicit
                      ``mode=``: the default ("fill_or_drop"-ish semantics
                      differing by op) hides out-of-bounds intent and costs
                      a select XLA can't always elide.
  memo-knob           ENGINE_KNOBS declares the ``memo`` knob with exactly
                      the off/admit/full/prefix ladder ("off" first — the
                      neutral arm is the default), and ``resolve_memo``
                      validates against the table, not a restated inline
                      spelling tuple that can drift from it.
  memo-schema         MEMOCACHE_SCHEMA_VERSION is ONE module-level int
                      literal in utils/memocache.py; every schema-stamping
                      dict there references the Name (a restated literal
                      would let the written and checked versions diverge),
                      and no other module re-assigns the constant.
  prefix-schema       PREFIXCACHE_SCHEMA_VERSION is ONE module-level int
                      literal in utils/memocache.py (no other module may
                      re-assign it); every prefix-cache entry dict there
                      (the depth/ckpt shape) stamps ``"schema":`` with
                      that exact Name; and every write-mode ``open`` in
                      the PrefixCache class body sits lexically inside a
                      ``with locked(...)`` block — checkpoints are shared
                      across serve-fleet processes, so an unlocked write
                      can tear a checkpoint another worker forks from.
  serve-knob          ENGINE_KNOBS declares ``serve_policy`` with exactly
                      the edf/fifo pair ("edf" first — the default), and
                      ``resolve_serve_policy`` validates against the table,
                      not a restated inline tuple. The generic knob-pattern
                      rule already demands the resolver/flag/bench-row
                      trio; this rule pins the ladder itself.
  serve-schema        SERVE_SCHEMA_VERSION is ONE module-level int literal
                      in serving/server.py; every ``"serve_schema":``
                      stamp in the package references the Name, and no
                      other module re-assigns the constant.
  host-sync           ``.item()``, ``float(<non-constant>)`` and
                      ``np.asarray(<device carry>)`` are banned inside
                      function bodies in ops/, kernels/ and parallel/ —
                      each is an implicit device->host sync that stalls
                      the dispatch pipeline and trips the runtime
                      sentry's transfer guard. Intentional harvest/
                      pack/termination functions are declared per-file
                      in HOST_SYNC_SITES; everything else must route
                      through utils/guards.guarded_get (explicit,
                      counted, guard-legal).
  cache-lock          every ``os.replace`` commit of a shared cache file
                      (utils/memocache.py, serving/executables.py) must
                      sit lexically inside a ``with locked(...)`` block
                      (utils/filelock) — an unlocked rename races
                      concurrent writers back to last-writer-wins.
  wal-append          the admission spool (serving/spool.py) is an
                      append-only fsynced journal: no ``os.replace``, no
                      write-mode ``open``, no raw ``.write()`` — durable
                      bytes go ONLY through utils/atomicio.fsync_append
                      (whose body must actually ``os.fsync``). Every
                      ``fsync_append``/``os.truncate`` site and every
                      call of the lock-holding helpers (``_replay``,
                      ``_append``, ``_requeue_or_poison``) sits lexically
                      inside ``with locked(...)`` or inside another
                      lock-holding helper's body — an unlocked append
                      interleaves records and an unlocked truncate can
                      eat a concurrent writer's fsynced tail.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from tools.staticcheck import Violation

STATE_PATH = "chandy_lamport_tpu/core/state.py"
CONFIG_PATH = "chandy_lamport_tpu/config.py"
GRAPHSHARD_PATH = "chandy_lamport_tpu/parallel/graphshard.py"
CLI_PATH = "chandy_lamport_tpu/cli.py"
BENCH_PATH = "chandy_lamport_tpu/bench.py"
MEMOCACHE_PATH = "chandy_lamport_tpu/utils/memocache.py"
SERVING_SERVER_PATH = "chandy_lamport_tpu/serving/server.py"
SERVING_EXEC_PATH = "chandy_lamport_tpu/serving/executables.py"
SPOOL_PATH = "chandy_lamport_tpu/serving/spool.py"
ATOMICIO_PATH = "chandy_lamport_tpu/utils/atomicio.py"
BATCH_PATH = "chandy_lamport_tpu/parallel/batch.py"

# the memo opt-in ladder; "off" first — the table order IS the contract
# (off is the default and the bit-identity baseline; "prefix" extends
# "full" with speculative forks from cached prefix checkpoints)
MEMO_SPELLINGS = ("off", "admit", "full", "prefix")

# the serving admission policies; "edf" first — the default the serve
# CLI/bench run unless the baseline is asked for explicitly
SERVE_SPELLINGS = ("edf", "fifo")

# modules whose function bodies are traced into jaxprs (directly or via the
# kernels/runners) — host clock/RNG imports are banned here
TRACED_MODULES = (
    "chandy_lamport_tpu/core/state.py",
    "chandy_lamport_tpu/ops/tick.py",
    "chandy_lamport_tpu/ops/delay_jax.py",
    "chandy_lamport_tpu/kernels/queue.py",
    "chandy_lamport_tpu/kernels/segment.py",
    "chandy_lamport_tpu/models/faults.py",
    "chandy_lamport_tpu/parallel/batch.py",
    "chandy_lamport_tpu/parallel/graphshard.py",
    "chandy_lamport_tpu/utils/tracing.py",
)

_SCATTER_ATTRS = {"add", "set", "mul", "min", "max", "subtract", "apply",
                  "divide", "power"}


def _parse(sources: Dict[str, str], path: str) -> Optional[ast.Module]:
    src = sources.get(path)
    if src is None:
        return None
    return ast.parse(src, filename=path)


def _assign_targets(node: ast.stmt) -> List[str]:
    if isinstance(node, ast.Assign):
        return [t.id for t in node.targets if isinstance(t, ast.Name)]
    if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
        return [node.target.id]
    return []


def _assign_value(node: ast.stmt):
    return node.value if isinstance(node, (ast.Assign, ast.AnnAssign)) else None


# ---------------------------------------------------------------------------
# err-bit-registry


def check_error_bits(sources: Dict[str, str]) -> List[Violation]:
    out: List[Violation] = []
    tree = _parse(sources, STATE_PATH)
    if tree is None:
        return [Violation("err-bit-registry", STATE_PATH,
                          "core/state.py not found in lint input")]

    consts: Dict[str, Tuple[int, int]] = {}  # name -> (value, lineno)
    registry_rows: List[Tuple[str, object, int]] = []
    num_bits_value: Optional[ast.expr] = None
    names_from_registry = False

    for node in tree.body:
        value = _assign_value(node)
        for name in _assign_targets(node):
            if name.startswith("ERR_") and isinstance(value, ast.Constant) \
                    and isinstance(value.value, int):
                consts[name] = (value.value, node.lineno)
            elif name == "ERROR_REGISTRY":
                for elt in getattr(value, "elts", []):
                    # rows may be constructor calls (ErrorBit(...)) or bare
                    # tuples, mirroring the CHECKPOINT_FORMAT_HISTORY parser
                    row = (elt.args if isinstance(elt, ast.Call)
                           else elt.elts if isinstance(elt, ast.Tuple)
                           else [])
                    if row:
                        row_name = (row[0].value
                                    if isinstance(row[0], ast.Constant)
                                    else None)
                        bit = row[1] if len(row) > 1 else None
                        registry_rows.append((row_name, bit, elt.lineno))
            elif name == "NUM_ERROR_BITS":
                num_bits_value = value
            elif name in ("ERROR_NAMES", "ERROR_BIT_NAMES"):
                if any(isinstance(n, ast.Name) and n.id == "ERROR_REGISTRY"
                       for n in ast.walk(value)):
                    names_from_registry = True

    for name, (v, ln) in sorted(consts.items(), key=lambda kv: kv[1][0]):
        if v <= 0 or v & (v - 1):
            out.append(Violation(
                "err-bit-registry", f"{STATE_PATH}:{ln}",
                f"{name} = {v} is not a power of two — error bits must "
                f"OR together losslessly"))
    by_value: Dict[int, List[str]] = {}
    for name, (v, _) in consts.items():
        by_value.setdefault(v, []).append(name)
    for v, names in sorted(by_value.items()):
        if len(names) > 1:
            ln = consts[names[1]][1]
            out.append(Violation(
                "err-bit-registry", f"{STATE_PATH}:{ln}",
                f"duplicate error bit {v}: {sorted(names)} — decode cannot "
                f"distinguish them"))
    want = {1 << i for i in range(len(by_value))}
    have = set(by_value)
    if consts and have != want and not any(
            v <= 0 or v & (v - 1) for v in have) and len(by_value) == len(consts):
        out.append(Violation(
            "err-bit-registry", STATE_PATH,
            f"error bits have gaps: {sorted(have)} != contiguous "
            f"{sorted(want)} — _por and the decode tables assume a dense "
            f"low-bit plane"))

    if not registry_rows:
        out.append(Violation(
            "err-bit-registry", STATE_PATH,
            "no ERROR_REGISTRY table — decode strings must live beside "
            "their bits in one declarative registry"))
    else:
        row_names = [r[0] for r in registry_rows]
        for row_name, bit, ln in registry_rows:
            if row_name not in consts:
                out.append(Violation(
                    "err-bit-registry", f"{STATE_PATH}:{ln}",
                    f"ERROR_REGISTRY row {row_name!r} has no matching ERR_ "
                    f"constant"))
            elif isinstance(bit, ast.Name) and bit.id != row_name:
                out.append(Violation(
                    "err-bit-registry", f"{STATE_PATH}:{ln}",
                    f"ERROR_REGISTRY row {row_name!r} binds bit {bit.id} — "
                    f"name and bit disagree"))
            elif isinstance(bit, ast.Constant) and \
                    bit.value != consts[row_name][0]:
                out.append(Violation(
                    "err-bit-registry", f"{STATE_PATH}:{ln}",
                    f"ERROR_REGISTRY row {row_name!r} restates bit "
                    f"{bit.value}, but {row_name} = {consts[row_name][0]}"))
        missing = sorted(set(consts) - set(row_names))
        if missing:
            out.append(Violation(
                "err-bit-registry", STATE_PATH,
                f"ERR_ constants with no ERROR_REGISTRY decode row: "
                f"{missing} — decode_errors would silently drop them"))
        dup_rows = sorted({n for n in row_names if row_names.count(n) > 1})
        if dup_rows:
            out.append(Violation(
                "err-bit-registry", STATE_PATH,
                f"duplicate ERROR_REGISTRY rows: {dup_rows}"))

    if num_bits_value is None or not (
            isinstance(num_bits_value, ast.Call)
            and isinstance(num_bits_value.func, ast.Name)
            and num_bits_value.func.id == "len"):
        out.append(Violation(
            "err-bit-registry", STATE_PATH,
            "NUM_ERROR_BITS must be len(ERROR_REGISTRY), not an independent "
            "literal that can drift"))
    if registry_rows and not names_from_registry:
        out.append(Violation(
            "err-bit-registry", STATE_PATH,
            "ERROR_NAMES/ERROR_BIT_NAMES must be derived from "
            "ERROR_REGISTRY, not hand-written dicts"))
    return out


# ---------------------------------------------------------------------------
# por-width


def check_por_width(sources: Dict[str, str]) -> List[Violation]:
    out: List[Violation] = []
    tree = _parse(sources, GRAPHSHARD_PATH)
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_por":
            uses_num_bits = any(
                isinstance(n, ast.Name) and n.id == "NUM_ERROR_BITS"
                for n in ast.walk(node))
            for call in ast.walk(node):
                if not isinstance(call, ast.Call) or not call.args:
                    continue
                fn = call.func
                is_arange = (isinstance(fn, ast.Attribute)
                             and fn.attr == "arange") or \
                            (isinstance(fn, ast.Name) and fn.id == "arange")
                if is_arange and isinstance(call.args[0], ast.Constant):
                    out.append(Violation(
                        "por-width", f"{GRAPHSHARD_PATH}:{call.lineno}",
                        f"_por hardcodes the error-plane width "
                        f"({call.args[0].value}); a new ERR_ bit would be "
                        f"silently dropped — use NUM_ERROR_BITS"))
            if not uses_num_bits:
                out.append(Violation(
                    "por-width", f"{GRAPHSHARD_PATH}:{node.lineno}",
                    "_por does not reference NUM_ERROR_BITS — the bit-plane "
                    "width must track the registry"))
    return out


# ---------------------------------------------------------------------------
# ckpt-version-literal + ckpt-history


def check_ckpt_versions(sources: Dict[str, str]) -> List[Violation]:
    out: List[Violation] = []
    for path, src in sorted(sources.items()):
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            targets = _assign_targets(node)
            value = _assign_value(node)
            for name in targets:
                if "FORMAT_VERSION" in name and \
                        isinstance(value, ast.Constant) and \
                        isinstance(value.value, int) and path != STATE_PATH:
                    out.append(Violation(
                        "ckpt-version-literal", f"{path}:{node.lineno}",
                        f"{name} = {value.value}: checkpoint version "
                        f"literals live only in the core/state.py history "
                        f"table — bind from CHECKPOINT_FORMAT_VERSION"))
            if isinstance(node, ast.Call):
                fn = node.func
                is_setattr = (isinstance(fn, ast.Name) and
                              fn.id == "setattr") or \
                             (isinstance(fn, ast.Attribute) and
                              fn.attr == "setattr")
                if is_setattr and any(
                        isinstance(a, ast.Constant) and
                        isinstance(a.value, str) and
                        "FORMAT_VERSION" in a.value for a in node.args):
                    out.append(Violation(
                        "ckpt-version-literal", f"{path}:{node.lineno}",
                        "setattr of a *FORMAT_VERSION* name — version "
                        "overrides outside the state.py table need an "
                        "allowlist reason"))

    tree = _parse(sources, STATE_PATH)
    if tree is None:
        return out
    history_rows: List[Tuple[int, int]] = []  # (version, lineno)
    version_value: Optional[ast.expr] = None
    version_line = 0
    for node in tree.body:
        value = _assign_value(node)
        for name in _assign_targets(node):
            if name == "CHECKPOINT_FORMAT_HISTORY":
                for elt in getattr(value, "elts", []):
                    if isinstance(elt, ast.Tuple) and elt.elts and \
                            isinstance(elt.elts[0], ast.Constant):
                        history_rows.append((elt.elts[0].value, elt.lineno))
            elif name == "CHECKPOINT_FORMAT_VERSION":
                version_value, version_line = value, node.lineno
    if not history_rows:
        out.append(Violation(
            "ckpt-history", STATE_PATH,
            "no CHECKPOINT_FORMAT_HISTORY table in core/state.py"))
        return out
    for i, (v, ln) in enumerate(history_rows):
        if v != i + 1:
            out.append(Violation(
                "ckpt-history", f"{STATE_PATH}:{ln}",
                f"history row {i} has version {v}, expected {i + 1} — "
                f"versions are consecutive from 1 so the supported-range "
                f"error message stays truthful"))
            break
    if isinstance(version_value, ast.Constant):
        out.append(Violation(
            "ckpt-history", f"{STATE_PATH}:{version_line}",
            f"CHECKPOINT_FORMAT_VERSION = {version_value.value} restates "
            f"the number — bind it to the last history row"))
    elif version_value is not None and not any(
            isinstance(n, ast.Name) and n.id == "CHECKPOINT_FORMAT_HISTORY"
            for n in ast.walk(version_value)):
        out.append(Violation(
            "ckpt-history", f"{STATE_PATH}:{version_line}",
            "CHECKPOINT_FORMAT_VERSION is not derived from "
            "CHECKPOINT_FORMAT_HISTORY"))
    return out


# ---------------------------------------------------------------------------
# knob-pattern


def check_knob_pattern(sources: Dict[str, str]) -> List[Violation]:
    out: List[Violation] = []
    tree = _parse(sources, CONFIG_PATH)
    if tree is None:
        return out
    knobs: List[str] = []
    for node in tree.body:
        value = _assign_value(node)
        if "ENGINE_KNOBS" in _assign_targets(node) and \
                isinstance(value, ast.Dict):
            knobs = [k.value for k in value.keys
                     if isinstance(k, ast.Constant)]
    if not knobs:
        return [Violation(
            "knob-pattern", CONFIG_PATH,
            "no ENGINE_KNOBS table in config.py — knob spellings must be "
            "declarative")]

    resolvers = set()
    for path, src in sources.items():
        if not path.startswith("chandy_lamport_tpu/"):
            continue
        try:
            t = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(t):
            if isinstance(node, ast.FunctionDef) and \
                    node.name.startswith("resolve_"):
                resolvers.add(node.name)

    flag_strings = set()
    bench_row_keys = set()
    for path in (CLI_PATH, BENCH_PATH):
        t = _parse(sources, path)
        if t is None:
            continue
        for node in ast.walk(t):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                flag_strings.add(node.value)
            if path == BENCH_PATH and isinstance(node, ast.Dict):
                bench_row_keys.update(
                    k.value for k in node.keys
                    if isinstance(k, ast.Constant) and
                    isinstance(k.value, str))

    for knob in knobs:
        if f"resolve_{knob}" not in resolvers:
            out.append(Violation(
                "knob-pattern", CONFIG_PATH,
                f"knob {knob!r} has no resolve_{knob}() — every knob needs "
                f"one place that turns 'auto' into a concrete engine"))
        flag = "--" + knob.replace("_", "-")
        if flag not in flag_strings:
            out.append(Violation(
                "knob-pattern", CONFIG_PATH,
                f"knob {knob!r} has no {flag} flag in cli.py or bench.py"))
        if knob not in bench_row_keys:
            out.append(Violation(
                "knob-pattern", CONFIG_PATH,
                f"knob {knob!r} is not stamped into any bench.py worker "
                f"row — sweep results would not record which engine ran"))

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "__post_init__":
            if not any(isinstance(n, ast.Name) and n.id == "ENGINE_KNOBS"
                       for n in ast.walk(node)):
                out.append(Violation(
                    "knob-pattern", f"{CONFIG_PATH}:{node.lineno}",
                    "SimConfig.__post_init__ validates knobs without "
                    "consulting ENGINE_KNOBS — inline tuples drift"))
    return out


# ---------------------------------------------------------------------------
# traced-import


def check_traced_imports(sources: Dict[str, str]) -> List[Violation]:
    out: List[Violation] = []
    for path in TRACED_MODULES:
        tree = _parse(sources, path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    if root in ("time", "random"):
                        out.append(Violation(
                            "traced-import", f"{path}:{node.lineno}",
                            f"import {alias.name} in a traced module — "
                            f"host clock/RNG must stay out of jitted code"))
            elif isinstance(node, ast.ImportFrom):
                root = (node.module or "").split(".")[0]
                if root in ("time", "random"):
                    out.append(Violation(
                        "traced-import", f"{path}:{node.lineno}",
                        f"from {node.module} import ... in a traced module"))
                if root == "numpy" and any(
                        a.name == "random" for a in node.names):
                    out.append(Violation(
                        "traced-import", f"{path}:{node.lineno}",
                        "numpy.random in a traced module — nondeterministic "
                        "under retrace"))
            elif isinstance(node, ast.Attribute) and node.attr == "random" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in ("np", "numpy"):
                out.append(Violation(
                    "traced-import", f"{path}:{node.lineno}",
                    "np.random use in a traced module — nondeterministic "
                    "under retrace; thread a jax PRNG key instead"))
    return out


# ---------------------------------------------------------------------------
# scatter-mode


def check_scatter_mode(sources: Dict[str, str]) -> List[Violation]:
    """``x.at[idx].add(v)`` without ``mode=`` in graphshard.py. The AST
    shape is Call(func=Attribute(value=Subscript(value=Attribute(attr='at')),
    attr='add'))."""
    out: List[Violation] = []
    tree = _parse(sources, GRAPHSHARD_PATH)
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SCATTER_ATTRS
                and isinstance(node.func.value, ast.Subscript)
                and isinstance(node.func.value.value, ast.Attribute)
                and node.func.value.value.attr == "at"):
            continue
        if not any(kw.arg == "mode" for kw in node.keywords):
            out.append(Violation(
                "scatter-mode", f"{GRAPHSHARD_PATH}:{node.lineno}",
                f".at[...].{node.func.attr}(...) without explicit mode= on "
                f"a sharded plane — state the out-of-bounds contract "
                f"(promise_in_bounds for pre-clipped indices, drop for "
                f"sentinel targets)"))
    return out


# ---------------------------------------------------------------------------
# memo-knob


def check_memo_knob(sources: Dict[str, str]) -> List[Violation]:
    """The memo knob's spellings live in ENGINE_KNOBS and nowhere else:
    the table row must be exactly the off/admit/full/prefix ladder (off
    first), and ``resolve_memo`` must consult the table by Name instead
    of restating the spellings in an inline tuple/list/set that would
    drift when a fifth memo level lands."""
    out: List[Violation] = []
    tree = _parse(sources, CONFIG_PATH)
    if tree is None:
        return out
    memo_row: Optional[Tuple[ast.expr, int]] = None
    for node in tree.body:
        value = _assign_value(node)
        if "ENGINE_KNOBS" in _assign_targets(node) and \
                isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and k.value == "memo":
                    memo_row = (v, k.lineno)
    if memo_row is None:
        return [Violation(
            "memo-knob", CONFIG_PATH,
            "ENGINE_KNOBS has no 'memo' row — the memoization ladder must "
            "be declared in the knob table like every other engine knob")]
    row_value, row_line = memo_row
    spellings = tuple(
        e.value for e in getattr(row_value, "elts", [])
        if isinstance(e, ast.Constant))
    if spellings != MEMO_SPELLINGS:
        out.append(Violation(
            "memo-knob", f"{CONFIG_PATH}:{row_line}",
            f"ENGINE_KNOBS['memo'] = {spellings!r}, expected "
            f"{MEMO_SPELLINGS!r} — 'off' leads (it is the default and the "
            f"bit-identity baseline) and the ladder is the documented "
            f"opt-in order"))

    resolver: Optional[Tuple[str, ast.FunctionDef]] = None
    for path, src in sources.items():
        if not path.startswith("chandy_lamport_tpu/"):
            continue
        try:
            t = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(t):
            if isinstance(node, ast.FunctionDef) and \
                    node.name == "resolve_memo":
                resolver = (path, node)
    if resolver is None:
        # knob-pattern already reports the missing resolver
        return out
    rpath, rnode = resolver
    if not any(isinstance(n, ast.Name) and n.id == "ENGINE_KNOBS"
               for n in ast.walk(rnode)):
        out.append(Violation(
            "memo-knob", f"{rpath}:{rnode.lineno}",
            "resolve_memo does not consult ENGINE_KNOBS — the accepted "
            "spellings must come from the table, not a local copy"))
    for n in ast.walk(rnode):
        if isinstance(n, (ast.Tuple, ast.List, ast.Set)):
            inline = {e.value for e in n.elts
                      if isinstance(e, ast.Constant)}
            if {"admit", "full"} <= inline:
                out.append(Violation(
                    "memo-knob", f"{rpath}:{n.lineno}",
                    f"resolve_memo restates the memo spellings inline "
                    f"({sorted(inline)}) — validate against "
                    f"ENGINE_KNOBS['memo'] so the ladder has one home"))
    return out


# ---------------------------------------------------------------------------
# memo-schema


def check_memo_schema(sources: Dict[str, str]) -> List[Violation]:
    """MEMOCACHE_SCHEMA_VERSION is a single named registry constant: one
    module-level int-literal assignment in utils/memocache.py, referenced
    by Name from every ``"schema":``-stamping dict there (a restated
    literal lets the written and the checked version diverge across a
    bump), and never re-assigned an int literal in any other module."""
    out: List[Violation] = []
    for path, src in sorted(sources.items()):
        if path == MEMOCACHE_PATH:
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            value = _assign_value(node)
            if "MEMOCACHE_SCHEMA_VERSION" in _assign_targets(node) and \
                    isinstance(value, ast.Constant) and \
                    isinstance(value.value, int):
                out.append(Violation(
                    "memo-schema", f"{path}:{node.lineno}",
                    f"MEMOCACHE_SCHEMA_VERSION = {value.value}: the memo "
                    f"cache schema version lives only in utils/memocache.py "
                    f"— import it, don't shadow it"))

    tree = _parse(sources, MEMOCACHE_PATH)
    if tree is None:
        return out + [Violation(
            "memo-schema", MEMOCACHE_PATH,
            "utils/memocache.py not found in lint input")]
    decls: List[Tuple[ast.stmt, Optional[ast.expr]]] = []
    for node in tree.body:
        if "MEMOCACHE_SCHEMA_VERSION" in _assign_targets(node):
            decls.append((node, _assign_value(node)))
    if not decls:
        out.append(Violation(
            "memo-schema", MEMOCACHE_PATH,
            "no module-level MEMOCACHE_SCHEMA_VERSION — the cache format "
            "needs one named registry constant"))
    elif len(decls) > 1:
        out.append(Violation(
            "memo-schema", f"{MEMOCACHE_PATH}:{decls[1][0].lineno}",
            "MEMOCACHE_SCHEMA_VERSION assigned more than once — one "
            "declaration, one value"))
    else:
        value = decls[0][1]
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, int)):
            out.append(Violation(
                "memo-schema", f"{MEMOCACHE_PATH}:{decls[0][0].lineno}",
                "MEMOCACHE_SCHEMA_VERSION must be a bare int literal — a "
                "computed version can change without a reviewable diff"))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value == "schema" and \
                    isinstance(v, ast.Constant) and isinstance(v.value, int):
                out.append(Violation(
                    "memo-schema", f"{MEMOCACHE_PATH}:{v.lineno}",
                    f"schema stamped with restated literal {v.value} — "
                    f"reference MEMOCACHE_SCHEMA_VERSION so write and "
                    f"check sites cannot diverge"))
    return out


# ---------------------------------------------------------------------------
# prefix-schema


def check_prefix_schema(sources: Dict[str, str]) -> List[Violation]:
    """PREFIXCACHE_SCHEMA_VERSION is a single named registry constant
    (one module-level int-literal assignment in utils/memocache.py,
    never re-assigned an int literal elsewhere), every prefix-cache
    entry dict there — recognizable by its depth/ckpt key shape —
    stamps ``"schema":`` with that exact Name, and every write-mode
    ``open`` inside the PrefixCache class sits lexically inside a
    ``with locked(...)`` block: the checkpoint file is shared across
    serve-fleet processes, and a torn or unlocked write is state
    another worker would FORK from (the memo-schema / cache-lock pair's
    discipline, specialized to the fork plane's store)."""
    out: List[Violation] = []
    for path, src in sorted(sources.items()):
        if path == MEMOCACHE_PATH:
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            value = _assign_value(node)
            if "PREFIXCACHE_SCHEMA_VERSION" in _assign_targets(node) and \
                    isinstance(value, ast.Constant) and \
                    isinstance(value.value, int):
                out.append(Violation(
                    "prefix-schema", f"{path}:{node.lineno}",
                    f"PREFIXCACHE_SCHEMA_VERSION = {value.value}: the "
                    f"prefix cache schema version lives only in "
                    f"utils/memocache.py — import it, don't shadow it"))

    tree = _parse(sources, MEMOCACHE_PATH)
    if tree is None:
        return out + [Violation(
            "prefix-schema", MEMOCACHE_PATH,
            "utils/memocache.py not found in lint input")]
    decls: List[Tuple[ast.stmt, Optional[ast.expr]]] = []
    for node in tree.body:
        if "PREFIXCACHE_SCHEMA_VERSION" in _assign_targets(node):
            decls.append((node, _assign_value(node)))
    if not decls:
        out.append(Violation(
            "prefix-schema", MEMOCACHE_PATH,
            "no module-level PREFIXCACHE_SCHEMA_VERSION — the checkpoint "
            "format needs one named registry constant"))
    elif len(decls) > 1:
        out.append(Violation(
            "prefix-schema", f"{MEMOCACHE_PATH}:{decls[1][0].lineno}",
            "PREFIXCACHE_SCHEMA_VERSION assigned more than once — one "
            "declaration, one value"))
    else:
        value = decls[0][1]
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, int)):
            out.append(Violation(
                "prefix-schema", f"{MEMOCACHE_PATH}:{decls[0][0].lineno}",
                "PREFIXCACHE_SCHEMA_VERSION must be a bare int literal — "
                "a computed version can change without a reviewable diff"))

    def entry_keys(node: ast.Dict) -> set:
        return {k.value for k in node.keys
                if isinstance(k, ast.Constant)}

    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        # a prefix-cache ENTRY dict: the depth/ckpt shape (memo summary
        # lines carry neither key, so the two planes can't cross-match)
        if not {"schema", "depth", "ckpt"} <= entry_keys(node):
            continue
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant) and k.value == "schema"):
                continue
            if not (isinstance(v, ast.Name)
                    and v.id == "PREFIXCACHE_SCHEMA_VERSION"):
                out.append(Violation(
                    "prefix-schema", f"{MEMOCACHE_PATH}:{v.lineno}",
                    "prefix cache entry stamps schema with something "
                    "other than the PREFIXCACHE_SCHEMA_VERSION Name — "
                    "write and check sites must not be able to diverge"))

    cls = next((n for n in tree.body
                if isinstance(n, ast.ClassDef)
                and n.name == "PrefixCache"), None)
    if cls is None:
        return out + [Violation(
            "prefix-schema", MEMOCACHE_PATH,
            "no PrefixCache class in utils/memocache.py")]

    def visit(node: ast.AST, locked_ctx: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locked_ctx = locked_ctx or any(
                _is_locked_ctx(item.context_expr) for item in node.items)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "open":
            mode = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "mode"),
                None)
            writes = (isinstance(mode, ast.Constant)
                      and isinstance(mode.value, str)
                      and any(c in mode.value for c in "wa+x"))
            if writes and not locked_ctx:
                out.append(Violation(
                    "prefix-schema", f"{MEMOCACHE_PATH}:{node.lineno}",
                    "PrefixCache opens its store for writing outside a "
                    "`with locked(...)` block (utils/filelock) — an "
                    "unlocked write can tear a checkpoint another "
                    "serve-fleet worker forks from"))
        for child in ast.iter_child_nodes(node):
            visit(child, locked_ctx)

    visit(cls, False)
    return out


# ---------------------------------------------------------------------------
# serve-knob


def check_serve_knob(sources: Dict[str, str]) -> List[Violation]:
    """The serving policy spellings live in ENGINE_KNOBS and nowhere
    else: the table row must be exactly the edf/fifo pair (edf first —
    it is the default), and ``resolve_serve_policy`` must consult the
    table by Name instead of restating the spellings in an inline
    tuple/list/set that would drift when a third policy lands. (The
    generic knob-pattern rule already demands the resolver, the
    ``--serve-policy`` flag and the bench row key.)"""
    out: List[Violation] = []
    tree = _parse(sources, CONFIG_PATH)
    if tree is None:
        return out
    row: Optional[Tuple[ast.expr, int]] = None
    for node in tree.body:
        value = _assign_value(node)
        if "ENGINE_KNOBS" in _assign_targets(node) and \
                isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and k.value == "serve_policy":
                    row = (v, k.lineno)
    if row is None:
        return [Violation(
            "serve-knob", CONFIG_PATH,
            "ENGINE_KNOBS has no 'serve_policy' row — the admission "
            "policies must be declared in the knob table like every other "
            "engine knob")]
    row_value, row_line = row
    spellings = tuple(
        e.value for e in getattr(row_value, "elts", [])
        if isinstance(e, ast.Constant))
    if spellings != SERVE_SPELLINGS:
        out.append(Violation(
            "serve-knob", f"{CONFIG_PATH}:{row_line}",
            f"ENGINE_KNOBS['serve_policy'] = {spellings!r}, expected "
            f"{SERVE_SPELLINGS!r} — 'edf' leads (it is the default) and "
            f"'fifo' is the arrival-order bench baseline"))

    resolver: Optional[Tuple[str, ast.FunctionDef]] = None
    for path, src in sources.items():
        if not path.startswith("chandy_lamport_tpu/"):
            continue
        try:
            t = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(t):
            if isinstance(node, ast.FunctionDef) and \
                    node.name == "resolve_serve_policy":
                resolver = (path, node)
    if resolver is None:
        # knob-pattern already reports the missing resolver
        return out
    rpath, rnode = resolver
    if not any(isinstance(n, ast.Name) and n.id == "ENGINE_KNOBS"
               for n in ast.walk(rnode)):
        out.append(Violation(
            "serve-knob", f"{rpath}:{rnode.lineno}",
            "resolve_serve_policy does not consult ENGINE_KNOBS — the "
            "accepted spellings must come from the table, not a local "
            "copy"))
    for n in ast.walk(rnode):
        if isinstance(n, (ast.Tuple, ast.List, ast.Set)):
            inline = {e.value for e in n.elts
                      if isinstance(e, ast.Constant)}
            if {"edf", "fifo"} <= inline:
                out.append(Violation(
                    "serve-knob", f"{rpath}:{n.lineno}",
                    f"resolve_serve_policy restates the policy spellings "
                    f"inline ({sorted(inline)}) — validate against "
                    f"ENGINE_KNOBS['serve_policy'] so they have one home"))
    return out


# ---------------------------------------------------------------------------
# serve-schema


def check_serve_schema(sources: Dict[str, str]) -> List[Violation]:
    """SERVE_SCHEMA_VERSION is a single named registry constant: one
    module-level int-literal assignment in serving/server.py, referenced
    by Name from every ``"serve_schema":``-stamping dict in the package
    (telemetry rows, checkpoint meta, report — a restated literal lets
    the written and the checked version diverge across a bump), and
    never re-assigned an int literal in any other module."""
    out: List[Violation] = []
    for path, src in sorted(sources.items()):
        if path == SERVING_SERVER_PATH:
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            value = _assign_value(node)
            if "SERVE_SCHEMA_VERSION" in _assign_targets(node) and \
                    isinstance(value, ast.Constant) and \
                    isinstance(value.value, int):
                out.append(Violation(
                    "serve-schema", f"{path}:{node.lineno}",
                    f"SERVE_SCHEMA_VERSION = {value.value}: the serve "
                    f"schema version lives only in serving/server.py — "
                    f"import it, don't shadow it"))

    tree = _parse(sources, SERVING_SERVER_PATH)
    if tree is None:
        return out + [Violation(
            "serve-schema", SERVING_SERVER_PATH,
            "serving/server.py not found in lint input")]
    decls: List[Tuple[ast.stmt, Optional[ast.expr]]] = []
    for node in tree.body:
        if "SERVE_SCHEMA_VERSION" in _assign_targets(node):
            decls.append((node, _assign_value(node)))
    if not decls:
        out.append(Violation(
            "serve-schema", SERVING_SERVER_PATH,
            "no module-level SERVE_SCHEMA_VERSION — the serve row format "
            "needs one named registry constant"))
    elif len(decls) > 1:
        out.append(Violation(
            "serve-schema", f"{SERVING_SERVER_PATH}:{decls[1][0].lineno}",
            "SERVE_SCHEMA_VERSION assigned more than once — one "
            "declaration, one value"))
    else:
        value = decls[0][1]
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, int)):
            out.append(Violation(
                "serve-schema",
                f"{SERVING_SERVER_PATH}:{decls[0][0].lineno}",
                "SERVE_SCHEMA_VERSION must be a bare int literal — a "
                "computed version can change without a reviewable diff"))
    for path, src in sorted(sources.items()):
        if not path.startswith("chandy_lamport_tpu/"):
            continue
        try:
            t = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(t):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and \
                        k.value == "serve_schema" and \
                        isinstance(v, ast.Constant) and \
                        isinstance(v.value, int):
                    out.append(Violation(
                        "serve-schema", f"{path}:{v.lineno}",
                        f"serve_schema stamped with restated literal "
                        f"{v.value} — reference SERVE_SCHEMA_VERSION so "
                        f"write and check sites cannot diverge"))
    return out


# ---------------------------------------------------------------------------
# host-sync

# device-loop packages: an implicit device->host sync here stalls the
# dispatch pipeline and (in an armed loop) trips the runtime sentry's
# transfer guard at dispatch time — this rule catches it at review time
HOST_SYNC_DIRS = ("chandy_lamport_tpu/ops/", "chandy_lamport_tpu/kernels/",
                  "chandy_lamport_tpu/parallel/")

# intentional host-side sites, declared per file + function name (BY
# SITE, mirroring runtime_sentry's per-row allowlists, never globally):
# pack_jobs/_job_digests run on host ScriptOps arrays before the carry
# upload; summarize harvests a state the caller already device_get
HOST_SYNC_SITES: Dict[str, FrozenSet[str]] = {
    BATCH_PATH: frozenset({"pack_jobs", "_job_digests", "summarize"}),
}

# the names the engine gives the device carry in loop bodies; asarray
# on anything rooted at one of these is a d2h of live device state
_HOST_SYNC_CARRIES = frozenset({"s", "state", "stream"})


def _root_name(expr: ast.expr) -> Optional[str]:
    """The base Name of an attribute/subscript chain (``s.q.tokens[i]``
    -> ``s``), or None."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _host_sync_call(node: ast.Call) -> Optional[str]:
    """Classify one Call as a banned host sync, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "item" and \
            not node.args and not node.keywords:
        return ".item() forces a device->host sync of a live array"
    if isinstance(fn, ast.Name) and fn.id == "float" and node.args and \
            not isinstance(node.args[0], ast.Constant):
        return "float(...) on a non-literal blocks on a d2h readback"
    if isinstance(fn, ast.Attribute) and fn.attr == "asarray" and \
            isinstance(fn.value, ast.Name) and \
            fn.value.id in ("np", "numpy") and node.args:
        root = _root_name(node.args[0])
        if root in _HOST_SYNC_CARRIES:
            return (f"np.asarray({root}...) copies the device carry "
                    f"back to host")
    return None


def check_host_sync(sources: Dict[str, str]) -> List[Violation]:
    """No implicit device->host syncs in function bodies under ops/,
    kernels/, parallel/ (module docstring). Intentional sites go in
    HOST_SYNC_SITES; loop-side reads route through
    utils/guards.guarded_get — explicit, counted, and legal under the
    armed transfer guard."""
    out: List[Violation] = []

    def visit(path: str, node: ast.AST, in_fn: bool,
              allowed: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in allowed:
                return
            for child in ast.iter_child_nodes(node):
                visit(path, child, True, allowed)
            return
        if in_fn and isinstance(node, ast.Call):
            why = _host_sync_call(node)
            if why is not None:
                out.append(Violation(
                    "host-sync", f"{path}:{node.lineno}",
                    f"{why} — use utils/guards.guarded_get at a named "
                    f"site, or declare the function in ast_lint."
                    f"HOST_SYNC_SITES if the sync is intentionally "
                    f"host-side"))
        for child in ast.iter_child_nodes(node):
            visit(path, child, in_fn, allowed)

    for path in sorted(sources):
        if not path.startswith(HOST_SYNC_DIRS):
            continue
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue
        visit(path, tree, False, HOST_SYNC_SITES.get(path, frozenset()))
    return out


# ---------------------------------------------------------------------------
# cache-lock

# files whose on-disk artifacts are shared across processes (the stream
# SummaryCache journal; the serve executable cache) — their os.replace
# commits must hold the utils/filelock lock or concurrent writers race
# back to last-writer-wins
CACHE_LOCK_PATHS = (MEMOCACHE_PATH, SERVING_EXEC_PATH)


def _is_locked_ctx(expr: ast.expr) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    fn = expr.func
    return (isinstance(fn, ast.Name) and fn.id == "locked") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "locked")


def check_cache_lock(sources: Dict[str, str]) -> List[Violation]:
    """Every ``os.replace`` in a shared-cache module sits lexically
    inside a ``with locked(...)`` block (module docstring). The lexical
    check is deliberately strict: passing fd ownership around would hide
    the lock scope from review."""
    out: List[Violation] = []

    def visit(path: str, node: ast.AST, locked_ctx: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locked_ctx = locked_ctx or any(
                _is_locked_ctx(item.context_expr) for item in node.items)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "replace" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "os":
            if not locked_ctx:
                out.append(Violation(
                    "cache-lock", f"{path}:{node.lineno}",
                    "os.replace of a shared cache file outside a `with "
                    "locked(...)` block (utils/filelock) — concurrent "
                    "writers race the rename to last-writer-wins"))
        for child in ast.iter_child_nodes(node):
            visit(path, child, locked_ctx)

    for path in CACHE_LOCK_PATHS:
        tree = _parse(sources, path)
        if tree is None:
            continue
        visit(path, tree, False)
    return out


# ---------------------------------------------------------------------------
# wal-append

# the spool's private mutators whose CALLERS hold the exclusive lock:
# their bodies may touch fsync_append/os.truncate/each other un-nested,
# but every call OF them from outside this set must sit lexically inside
# ``with locked(...)`` — the lexical discipline mirrors cache-lock
WAL_LOCK_HELPERS = frozenset({"_replay", "_append", "_requeue_or_poison"})


def _call_name(node: ast.Call) -> Optional[str]:
    """The terminal name of a call target (``self._append`` ->
    ``_append``, ``fsync_append`` -> ``fsync_append``)."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _open_mode(node: ast.Call) -> Optional[str]:
    if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
        return None
    mode = None
    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return mode if isinstance(mode, str) else ("r" if mode is None else None)


def check_wal_append(sources: Dict[str, str]) -> List[Violation]:
    """The admission journal is append-only and fsync-disciplined
    (module docstring): serving/spool.py may not rename, rewrite or
    raw-``.write()`` the journal — bytes land only via
    utils/atomicio.fsync_append, and both it and ``os.truncate`` (the
    torn-tail repair) run under the exclusive lock, either lexically or
    inside a WAL_LOCK_HELPERS body whose own call sites are checked the
    same way."""
    out: List[Violation] = []
    tree = _parse(sources, SPOOL_PATH)
    if tree is not None:
        def visit(node: ast.AST, locked_ctx: bool, fn_name: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_name = node.name
                locked_ctx = node.name in WAL_LOCK_HELPERS
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                locked_ctx = locked_ctx or any(
                    _is_locked_ctx(item.context_expr) for item in node.items)
            elif isinstance(node, ast.Call):
                name = _call_name(node)
                if name == "replace" and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "os":
                    out.append(Violation(
                        "wal-append", f"{SPOOL_PATH}:{node.lineno}",
                        "os.replace in the spool — the admission journal "
                        "is append-only; a rename rewrites acknowledged "
                        "history"))
                elif name == "write":
                    out.append(Violation(
                        "wal-append", f"{SPOOL_PATH}:{node.lineno}",
                        "raw .write() in the spool — durable journal "
                        "bytes go only through utils/atomicio."
                        "fsync_append, so every acknowledged record is "
                        "on disk before return"))
                elif name == "open":
                    mode = _open_mode(node)
                    if mode is None or any(c in mode for c in "wx+"):
                        out.append(Violation(
                            "wal-append", f"{SPOOL_PATH}:{node.lineno}",
                            f"open(..., {mode!r}) in the spool — only "
                            f"read ('rb') and append ('ab') modes are "
                            f"legal on an append-only journal"))
                elif name == "fsync_append" or (
                        name == "truncate" and
                        isinstance(node.func, ast.Attribute) and
                        isinstance(node.func.value, ast.Name) and
                        node.func.value.id == "os"):
                    if not locked_ctx:
                        out.append(Violation(
                            "wal-append", f"{SPOOL_PATH}:{node.lineno}",
                            f"{name} outside the exclusive lock — an "
                            f"unlocked append interleaves records and an "
                            f"unlocked truncate can eat a concurrent "
                            f"writer's fsynced tail; wrap in `with "
                            f"locked(...)` or a WAL_LOCK_HELPERS body"))
                elif name in WAL_LOCK_HELPERS and not locked_ctx:
                    out.append(Violation(
                        "wal-append", f"{SPOOL_PATH}:{node.lineno}",
                        f"{name}() called outside `with locked(...)` — "
                        f"the spool's private mutators assume their "
                        f"caller holds the exclusive lock"))
            for child in ast.iter_child_nodes(node):
                visit(child, locked_ctx, fn_name)

        visit(tree, False, "")

    atree = _parse(sources, ATOMICIO_PATH)
    if atree is not None:
        fsync_fn = None
        for node in ast.walk(atree):
            if isinstance(node, ast.FunctionDef) and \
                    node.name == "fsync_append":
                fsync_fn = node
        if fsync_fn is None:
            if tree is not None:
                out.append(Violation(
                    "wal-append", ATOMICIO_PATH,
                    "no fsync_append in utils/atomicio.py — the spool's "
                    "named durable-append helper is missing"))
        elif not any(
                isinstance(n, ast.Call) and _call_name(n) == "fsync" and
                isinstance(n.func, ast.Attribute) and
                isinstance(n.func.value, ast.Name) and
                n.func.value.id == "os"
                for n in ast.walk(fsync_fn)):
            out.append(Violation(
                "wal-append", f"{ATOMICIO_PATH}:{fsync_fn.lineno}",
                "fsync_append does not call os.fsync — without it the "
                "WAL's returning-IS-the-acknowledgement contract is a "
                "lie after a power cut"))
    return out


# ---------------------------------------------------------------------------
# driver

ALL_RULES = (
    check_error_bits,
    check_por_width,
    check_ckpt_versions,
    check_knob_pattern,
    check_traced_imports,
    check_scatter_mode,
    check_memo_knob,
    check_memo_schema,
    check_prefix_schema,
    check_serve_knob,
    check_serve_schema,
    check_host_sync,
    check_cache_lock,
    check_wal_append,
)


def lint_sources(sources: Dict[str, str]) -> List[Violation]:
    out: List[Violation] = []
    for rule in ALL_RULES:
        out.extend(rule(sources))
    return out


def load_tree(root: str) -> Dict[str, str]:
    """Collect the lint input: every .py under chandy_lamport_tpu/ and
    tests/, keyed by repo-relative path."""
    sources: Dict[str, str] = {}
    for top in ("chandy_lamport_tpu", "tests"):
        base = os.path.join(root, top)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, "r", encoding="utf-8") as f:
                    sources[rel] = f.read()
    return sources


def lint_tree(root: str) -> List[Violation]:
    return lint_sources(load_tree(root))
