"""Plane 3: static HLO cost budgets across the entry-arm matrix.

The jaxpr plane (jaxpr_audit.py) audits trace STRUCTURE; this plane
audits trace COST. Every registered entry arm — the same ~69-arm
`{queue,comm,kernel,memo,serve_policy}` knob matrix iter_entry_builders
yields — is lowered and backend-compiled, and the optimized HLO module
is walked into a static cost row per arm:

  flops / bytes_accessed   XLA's own ``Compiled.cost_analysis()`` —
                           modeled FLOPs and HBM bytes moved per call.
  argument/output/temp     ``Compiled.memory_analysis()`` buffer sizes;
  peak_buffer_bytes        arg + out + temp − aliased, the static
                           peak-live estimate (donation shows up here
                           as alias credit).
  collective_count/bytes   per-op counts of all-reduce / all-gather /
  + per-collective counts  reduce-scatter / all-to-all /
                           collective-permute defs in the optimized
                           module, plus the summed byte size of their
                           result shapes — the cross-shard traffic the
                           comm_engine knob exists to shrink.
  scatter/gather/fusion    op-shape counts for the queue engines' core
                           primitives and XLA's fusion granularity.

Rows are checked against ``cost_budgets.json`` — same schema-versioned,
recorded-jax-version, regenerate-in-the-same-commit discipline as the
trace fingerprints (jaxpr_audit.load_registry). Budgets are CEILINGS:
an arm may come in under budget (that is an improvement — regenerate to
re-pin), but a PR that adds an all-gather to the tick or regrows the
[E,C] round-trip exceeds its recorded ceiling and fails
``python -m tools.staticcheck`` with a named metric diff. Floats get
FLOAT_TOL headroom (cost_analysis models wobble slightly across
rebuilds of the same program); counts are exact ceilings.

FS_GPlib (PAPERS.md) budgets propagation kernels by modeled bytes and
FLOPs rather than wall clock; this plane is that discipline applied to
every compiled surface of the engine, on every PR, with no hardware in
the loop.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Sequence

from tools.staticcheck import Violation
from tools.staticcheck.jaxpr_audit import (
    Entry,
    ensure_env,
    iter_entry_builders,
)

BUDGETS_PATH = os.path.join(os.path.dirname(__file__), "cost_budgets.json")

BUDGET_SCHEMA = 1

# relative headroom for float metrics (flops / bytes): XLA's analytical
# model is deterministic for a fixed program, but equivalent rebuilds
# (e.g. a refactor that renames a fusion) can wobble it at the margin
FLOAT_TOL = 0.01

# one mutually-exclusive HLO opcode per collective family ("-start"
# suffixed async forms count as the op; "-done" halves do not define a
# new transfer)
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "token": 0,
}

# an HLO def site: `%name = <shape> opcode(`; the shape is a single
# `dtype[dims]{layout}` or a tuple of them
_DEF_RE = re.compile(
    r"=\s+(?P<shape>\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)"
    r"\s+(?P<op>[a-z][a-z0-9-]*)\(")

_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")

# budget metrics: floats get FLOAT_TOL headroom, counts are exact.
# hbm_model_bytes is the ANALYTIC HBM round trip of the megatick arms
# (kernels/megatick.hbm_round_trip_model, merged in via Entry.extra_cost)
# — the metric that proves the fusion: the fused arm's recorded ceiling
# sits at ~1/K of its split twin's, which compiled-bytes can't show for
# interpret-mode Pallas
FLOAT_METRICS = ("flops", "bytes_accessed", "argument_bytes",
                 "output_bytes", "temp_bytes", "peak_buffer_bytes",
                 "collective_bytes", "hbm_model_bytes")


def _shape_bytes(shape: str) -> int:
    """Byte size of an HLO result shape string (tuples sum elementwise)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def hlo_op_stats(hlo_text: str) -> Dict[str, float]:
    """Walk an optimized HLO module's def sites into the op-count half of
    the cost row (module docstring). Fusion-interior defs count too —
    a gather inside a fused computation is still a gather the backend
    executes."""
    counts: Dict[str, int] = {op: 0 for op in COLLECTIVE_OPS}
    counts["scatter"] = 0
    counts["gather"] = 0
    counts["fusion"] = 0
    collective_bytes = 0
    for m in _DEF_RE.finditer(hlo_text):
        op = m.group("op")
        if op.endswith("-start"):
            op = op[:-len("-start")]
        elif op.endswith("-done"):
            continue
        if op in COLLECTIVE_OPS:
            counts[op] += 1
            collective_bytes += _shape_bytes(m.group("shape"))
        elif op in ("scatter", "gather", "fusion"):
            counts[op] += 1
    row: Dict[str, float] = {
        f"{op.replace('-', '_')}_count": counts[op] for op in COLLECTIVE_OPS}
    row["scatter_count"] = counts["scatter"]
    row["gather_count"] = counts["gather"]
    row["fusion_count"] = counts["fusion"]
    row["collective_count"] = sum(counts[op] for op in COLLECTIVE_OPS)
    row["collective_bytes"] = collective_bytes
    return row


def measure_compiled(compiled) -> Dict[str, float]:
    """The static cost row of one backend-compiled executable."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jaxlibs: one dict per device
        ca = ca[0] if ca else {}
    ca = ca or {}
    row: Dict[str, float] = {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }
    mem = compiled.memory_analysis()
    if mem is not None:
        arg = int(getattr(mem, "argument_size_in_bytes", 0))
        out = int(getattr(mem, "output_size_in_bytes", 0))
        tmp = int(getattr(mem, "temp_size_in_bytes", 0))
        alias = int(getattr(mem, "alias_size_in_bytes", 0))
        row.update(argument_bytes=arg, output_bytes=out, temp_bytes=tmp,
                   peak_buffer_bytes=max(arg + out + tmp - alias, 0))
    row.update(hlo_op_stats(compiled.as_text()))
    return row


def measure_entry(entry: Entry) -> Dict[str, float]:
    """Lower + compile one audit entry and measure it. Prefers the
    user-facing jitted callable (donation aliasing is part of the peak-
    buffer story); bare fns are jitted here."""
    import jax
    fn = entry.jit_fn
    if fn is None:
        fn = entry.fn if hasattr(entry.fn, "lower") else jax.jit(entry.fn)
    row = measure_compiled(fn.lower(*entry.args).compile())
    if entry.extra_cost:
        row.update(entry.extra_cost)
    return row


# ---------------------------------------------------------------------------
# budget registry (fingerprints.json discipline: schema + jax stamped,
# regenerated in the same commit as an intentional cost change)

# set by audit(): human-readable note when the registry comparison was
# skipped (version mismatch); __main__ surfaces it in the report
_LAST_BUDGET_NOTE: Optional[str] = None


def load_budgets(path: Optional[str] = None):
    """Returns (entries, recorded_jax_version)."""
    path = path or BUDGETS_PATH
    if not os.path.exists(path):
        return {}, None
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(
            f"cost budgets {path}: not a schema-{BUDGET_SCHEMA} registry")
    if data.get("schema") != BUDGET_SCHEMA:
        raise ValueError(
            f"cost budgets {path}: schema {data.get('schema')!r}; this "
            f"build reads only v{BUDGET_SCHEMA} — regenerate with "
            f"--budgets-update")
    return dict(data["entries"]), data.get("jax")


def save_budgets(entries: Dict[str, Dict[str, float]],
                 path: Optional[str] = None) -> None:
    import jax
    path = path or BUDGETS_PATH
    payload = {
        "schema": BUDGET_SCHEMA,
        "jax": jax.__version__,
        "entries": {k: dict(sorted(v.items()))
                    for k, v in sorted(entries.items())},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def check_against_budget(key: str, row: Dict[str, float],
                         budget: Optional[Dict[str, float]]
                         ) -> List[Violation]:
    """Ceiling comparison of a measured cost row against its recorded
    budget (module docstring semantics). A missing budget is itself a
    violation: every arm must be pinned or the plane is blind to it."""
    if budget is None:
        return [Violation(
            "cost-budget", key,
            "no recorded cost budget — run "
            "`python -m tools.staticcheck --budgets-update`")]
    out: List[Violation] = []
    for metric in sorted(row):
        have = row[metric]
        want = budget.get(metric)
        if want is None:
            # a metric this build measures but the registry predates:
            # only a regenerate can pin it; don't fail retroactively
            continue
        if metric in FLOAT_METRICS:
            ceiling = float(want) * (1.0 + FLOAT_TOL)
            over = float(have) > ceiling and float(have) - float(want) > 1.0
        else:
            over = int(have) > int(want)
        if over:
            pct = (100.0 * (float(have) - float(want)) / float(want)
                   if float(want) else float("inf"))
            out.append(Violation(
                "cost-budget", key,
                f"{metric} regressed: measured {have:g} > budget "
                f"{want:g} (+{pct:.1f}%) — an intentional cost change "
                f"must regenerate cost_budgets.json in the same commit"))
    return out


def audit(mode: str = "full", *, check_budgets: bool = True,
          update_budgets: bool = False,
          keys: Optional[Sequence[str]] = None):
    """Run the cost plane. Returns (violations, audited_keys, fresh_rows).

    Mirrors jaxpr_audit.audit: fast mode measures the 5-arm tier-1
    subset, full the whole matrix; ``update_budgets`` re-pins measured
    arms instead of comparing; a registry recorded under a different jax
    version is skipped with a note (XLA's cost model and fusion
    decisions legitimately move across toolchains)."""
    global _LAST_BUDGET_NOTE
    ensure_env()
    _LAST_BUDGET_NOTE = None
    registry = None
    if check_budgets and not update_budgets:
        import jax
        entries, recorded_jax = load_budgets()
        if recorded_jax is not None and recorded_jax != jax.__version__:
            _LAST_BUDGET_NOTE = (
                f"cost budgets were generated under jax {recorded_jax} "
                f"but this run is jax {jax.__version__}; comparison "
                f"skipped — run --budgets-update to re-pin")
        else:
            registry = entries
    violations: List[Violation] = []
    audited: List[str] = []
    fresh: Dict[str, Dict[str, float]] = {}
    for key, build in iter_entry_builders(mode):
        if keys is not None and key not in keys:
            continue
        try:
            entry = build()
            row = measure_entry(entry)
        except Exception as exc:
            violations.append(Violation(
                "entry-build", key,
                f"could not lower/compile the costed entry: "
                f"{type(exc).__name__}: {exc}"))
            continue
        if registry is not None:
            violations.extend(
                check_against_budget(key, row, registry.get(key)))
        audited.append(key)
        fresh[key] = row
    if update_budgets:
        merged, _ = load_budgets()
        merged.update(fresh)
        save_budgets(merged)
    return violations, audited, fresh
