"""Plane 1: trace-level audit of every public jitted entry point.

Each entry builder constructs a tiny instance of one jitted surface —
TickKernel ticks across the knob matrix, the batched storm step, the
streaming step, the graph-sharded dispatch, the Pallas kernels under
interpret=True — and returns the callable plus example arguments. The
audit traces it with ``jax.make_jaxpr`` and checks the trace itself:

  f64-in-trace        no float64 aval anywhere (weak-typed promotion bugs
                      surface here long before a TPU run fails on them)
  i64-in-trace        no int64/uint64 aval: the state plan is i32/u32 and
                      an unintended promotion doubles HBM silently
  state-leaf-dtype    output state leaves are int32/uint32/bool only
  const-capture       total jaxpr consts bytes under the per-entry budget
                      (the failure mode that broke 8k-node remote
                      compilation: GB-scale incidence constants in HLO)
  donation            entries built with donate_argnums actually alias
                      their carry (``tf.aliasing_output`` in the lowering;
                      a donation silently dropped = 2x state HBM)
  host-callback       no debug_callback/io_callback/pure_callback in hot
                      paths — a stray jax.debug.print syncs every step
  ppermute-bijection  every ppermute permutation is a bijection (a dropped
                      or duplicated shard lane deadlocks the halo ring)
  collective-axis     every named collective's axis exists in the entry's
                      mesh (and entries without a mesh trace no named
                      collectives at all)
  fingerprint         sha256 of the normalized trace structure (primitive
                      names, aval signatures, value-like params, consts
                      signature) matches fingerprints.json; fails when a
                      trace changes without regeneration
                      (``--fingerprints-update``); skipped with a report
                      note when the registry's recorded jax version
                      differs from the running one

Callers must set up the audit environment BEFORE importing jax (see
``ensure_env``): CPU backend, 8 host devices, x64 enabled — the same
canonical environment conftest.py and cli.py pin, so fingerprints agree
between the CLI and the test suite.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

from tools.staticcheck import Violation

FINGERPRINTS_PATH = os.path.join(os.path.dirname(__file__),
                                 "fingerprints.json")

# set by audit() when the registry's recorded jax version does not match
# the running one and the fingerprint comparison was therefore skipped;
# surfaced in the JSON report so a skipped gate is visible, not silent
_LAST_REGISTRY_NOTE: Optional[str] = None

# primitives that round-trip through the host: forbidden in every audited
# entry (the flight recorder exists precisely so hot paths never need them)
HOST_CALLBACK_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "callback",
    "outside_call", "host_callback_call", "debug_print",
})

# eqn params that carry collective axis names
_AXIS_PARAM_KEYS = ("axis_name", "axes", "axis_index_groups_axis")

DEFAULT_CONST_BUDGET = 4 << 20  # bytes; audit graphs are tiny, so generous


def ensure_env() -> None:
    """Pin the canonical audit environment. Must run before jax is first
    imported; no-op (with a check) afterwards."""
    import sys
    if "jax" in sys.modules:
        import jax
        if jax.default_backend() not in ("cpu",):
            raise RuntimeError(
                "staticcheck must run on the CPU backend (jax was already "
                f"imported with backend {jax.default_backend()!r})")
        jax.config.update("jax_enable_x64", True)
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_enable_x64", True)


@dataclasses.dataclass
class Entry:
    """One audited jitted surface. ``fn``/``args`` feed make_jaxpr;
    ``jit_fn`` (when set) is the user-facing jitted callable, lowered to
    verify donation of ``donated`` argnums. ``axis_names`` are the mesh
    axes named collectives may reference (empty = none allowed).
    ``state_out`` applies the int32/uint32/bool whitelist to every output
    leaf (entries returning DenseState-only pytrees)."""

    key: str
    fn: Callable
    args: Tuple[Any, ...]
    jit_fn: Optional[Callable] = None
    donated: Tuple[int, ...] = ()
    axis_names: FrozenSet[str] = frozenset()
    state_out: bool = True
    const_budget: int = DEFAULT_CONST_BUDGET
    # analytic metrics merged into the cost plane's measured row (the
    # fused-megatick arms pin hbm_model_bytes here: interpret-mode Pallas
    # inlines the kernel into stock HLO, so XLA's bytes_accessed cannot
    # see the fusion — kernels/megatick.hbm_round_trip_model can)
    extra_cost: Optional[Dict[str, float]] = None


# ---------------------------------------------------------------------------
# fixtures


def _delay(kind: str = "hash"):
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, make_fast_delay
    if kind == "fixed":
        return FixedJaxDelay(2)
    return make_fast_delay("hash", 7)


def _cfg(**overrides):
    from chandy_lamport_tpu.config import SimConfig
    return SimConfig.for_workload(snapshots=2, max_recorded=32, **overrides)


def _tick_topo(n: int):
    from chandy_lamport_tpu.core.state import DenseTopology
    from chandy_lamport_tpu.models.workloads import ring_topology
    return DenseTopology(ring_topology(n, tokens=16))


def _faults():
    from chandy_lamport_tpu.models.faults import JaxFaults
    return JaxFaults(3, drop_rate=0.05)


def _trace():
    from chandy_lamport_tpu.utils.tracing import JaxTrace
    return JaxTrace(capacity=0)


def _tick_kernel(*, exact_impl="cascade", marker_mode="ring",
                 queue_engine="gather", kernel_engine="xla",
                 faults=False, trace=False, n=8):
    from chandy_lamport_tpu.ops.tick import TickKernel
    cfg = _cfg(trace_capacity=64 if trace else 0)
    topo = _tick_topo(n)
    delay = _delay()
    kern = TickKernel(
        topo, cfg, delay, marker_mode=marker_mode, exact_impl=exact_impl,
        megatick=2, queue_engine=queue_engine, kernel_engine=kernel_engine,
        faults=_faults() if faults else None,
        trace=_trace() if trace else None)
    from chandy_lamport_tpu.core.state import init_state
    state = init_state(topo, cfg, delay.init_state())
    return kern, state


# ---------------------------------------------------------------------------
# entry builders (each returns an Entry; construction is lazy so --fast
# never pays for the arms it skips)


def _tick_entry(impl, qe, ke, faults, trace) -> Entry:
    kern, state = _tick_kernel(exact_impl=impl, queue_engine=qe,
                               kernel_engine=ke, faults=faults, trace=trace)
    key = (f"tick.{impl}.q={qe}.k={ke}.f={int(faults)}.t={int(trace)}")
    return Entry(key=key, fn=kern._exact_tick, args=(state,),
                 jit_fn=kern.tick, donated=(0,))


def _fused_kernel(*, exact_impl="cascade", queue_engine="gather",
                  fused="on", tile="off", faults=False, supervised=False,
                  traced=False, n=8):
    """A TickKernel on the one-kernel-megatick arm (kernels/megatick.py):
    kernel_engine=pallas + megatick=4 + fused_tick='on' runs the whole
    K-tick loop as ONE interpret-mode Pallas kernel; the 'off' twin is
    the split-kernel baseline the cost plane compares against. K=4 so
    the hbm_model_bytes ratio (fused reads the carry once, split once
    per tick) clears the <=50% gate on the faulted arms too, where the
    streamed plane bytes are common to both sides. ``tile='on'`` forces
    the tiled-state layout (rings stream HBM<->VMEM once per step —
    its own documented gate, see tools/analyze --cost); ``supervised``
    arms the snapshot supervisor and ``traced`` the flight recorder —
    the production arms ISSUE-16 un-refused."""
    from chandy_lamport_tpu.ops.tick import TickKernel
    cfg = _cfg(**({"snapshot_timeout": 5, "snapshot_retries": 2}
                  if supervised else {}),
               **({"trace_capacity": 64} if traced else {}))
    topo = _tick_topo(n)
    delay = _delay()
    kern = TickKernel(
        topo, cfg, delay, exact_impl=exact_impl, megatick=4,
        queue_engine=queue_engine, kernel_engine="pallas",
        faults=_faults() if faults else None,
        trace=_trace() if traced else None,
        fused_tick=fused, fused_tile=tile)
    from chandy_lamport_tpu.core.state import init_state
    state = init_state(topo, cfg, delay.init_state(),
                       fault_key=3 if faults else 0)
    return kern, state


def _fused_extra(kern, state, faults: bool, length: int) -> Dict[str, float]:
    """The analytic HBM round-trip metrics for one fused/split arm
    (megatick.hbm_round_trip_model): the cost plane pins both so the
    fused arm's ceiling provably sits at <= 50% of the split arm's —
    and the TILED fused arm's at <= the tiled gate (the rings leave the
    resident set but re-cross HBM once per step; tools/analyze --cost
    prints the cross-check rows)."""
    from chandy_lamport_tpu.kernels import megatick as mt
    state_bytes = mt.pytree_bytes(state)
    plane_bytes = (length * (8 * kern.topo.e + 2 * kern.topo.n) * 4
                   if faults else 0)
    tiled = getattr(kern, "fused_tile", "off") == "on"
    ring_bytes = 2 * kern.topo.e * kern.cfg.queue_capacity * 4
    return {"hbm_model_bytes": float(mt.hbm_round_trip_model(
        state_bytes, plane_bytes, length, fused=kern.fused == "on",
        ring_bytes=ring_bytes, tiled=tiled))}


def _fused_entry(impl, qe, faults, surface, fused="on", tile="off",
                 supervised=False, traced=False) -> Entry:
    import jax.numpy as jnp
    kern, state = _fused_kernel(exact_impl=impl, queue_engine=qe,
                                fused=fused, tile=tile, faults=faults,
                                supervised=supervised, traced=traced)
    tag = "fused" if fused == "on" else "megasplit"
    if tile == "on":
        tag += ".tiled"
    if supervised:
        tag += ".sup"
    if traced:
        tag += ".tr"
    key = f"tick.{tag}.{impl}.q={qe}.f={int(faults)}.{surface}"
    extra = _fused_extra(kern, state, faults, kern.megatick)
    if surface == "run_ticks":
        return Entry(key=key, fn=kern._run_ticks,
                     args=(state, jnp.int32(4)), jit_fn=kern.run_ticks,
                     donated=(0,), extra_cost=extra)
    return Entry(key=key, fn=kern._drain_and_flush, args=(state,),
                 jit_fn=kern.drain_and_flush, donated=(0,),
                 extra_cost=extra)


def _sync_entry(qe, ke, faults, trace) -> Entry:
    kern, state = _tick_kernel(exact_impl="cascade", marker_mode="split",
                               queue_engine=qe, kernel_engine=ke,
                               faults=faults, trace=trace)
    key = f"sync.q={qe}.k={ke}.f={int(faults)}.t={int(trace)}"
    return Entry(key=key, fn=kern._sync_tick, args=(state,))


def _loop_entry(name: str) -> Entry:
    import jax.numpy as jnp
    kern, state = _tick_kernel()
    if name == "run_ticks":
        return Entry(key="tick.run_ticks", fn=kern._run_ticks,
                     args=(state, jnp.int32(4)), jit_fn=kern.run_ticks,
                     donated=(0,))
    if name == "drain":
        return Entry(key="tick.drain_and_flush", fn=kern._drain_and_flush,
                     args=(state,), jit_fn=kern.drain_and_flush, donated=(0,))
    if name == "inject_send":
        return Entry(key="tick.inject_send", fn=kern._inject_send,
                     args=(state, jnp.int32(0), jnp.int32(3)),
                     jit_fn=kern.inject_send, donated=(0,))
    if name == "inject_snapshot":
        return Entry(key="tick.inject_snapshot", fn=kern._inject_snapshot,
                     args=(state, jnp.int32(1)),
                     jit_fn=kern.inject_snapshot, donated=(0,))
    if name == "sync_drain":
        kern, state = _tick_kernel(marker_mode="split")
        return Entry(key="sync.drain_and_flush",
                     fn=kern._sync_drain_and_flush, args=(state,))
    raise KeyError(name)


def _batch_runner(scheduler: str, trace=False, memo="off"):
    from chandy_lamport_tpu.models.workloads import ring_topology
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    return BatchedRunner(
        ring_topology(8, tokens=16), _cfg(trace_capacity=64 if trace else 0),
        _delay(), 2, scheduler=scheduler, megatick=2, memo=memo)


def _storm_entry(scheduler: str) -> Entry:
    import jax.numpy as jnp
    from chandy_lamport_tpu.models.workloads import (
        staggered_snapshots,
        storm_program,
    )
    runner = _batch_runner(scheduler)
    prog = storm_program(runner.topo, phases=2, amount=1,
                         snapshot_phases=staggered_snapshots(runner.topo, 1))
    state = runner.init_batch()
    args = (state, tuple(jnp.asarray(x) for x in (prog.amounts, prog.snap)))
    return Entry(key=f"batch.storm.{scheduler}", fn=runner._run_storm,
                 args=args, jit_fn=runner._run_storm, donated=(0,),
                 state_out=False)


def _stream_entry(memo: str = "off") -> Entry:
    import jax
    import jax.numpy as jnp
    from chandy_lamport_tpu.models.workloads import stream_jobs
    from chandy_lamport_tpu.models.workloads import ring_topology
    runner = _batch_runner("sync", memo=memo)
    jobs = stream_jobs(ring_topology(8, tokens=16), 4, seed=5,
                       base_phases=2, max_phases=4)
    pool = runner.pack_jobs(jobs, content_keys=True if memo != "off"
                            else None)
    stream = runner.init_stream(pool)
    state = runner.init_batch()
    pool_dev = jax.tree_util.tree_map(jnp.asarray, pool)
    step = runner._stream_step(2, 8, False)
    if memo == "off":
        return Entry(key="batch.stream.step", fn=step,
                     args=(state, stream, pool_dev), jit_fn=step,
                     donated=(0, 1), state_out=False)
    # the memo step takes the admission indirection (execution order +
    # follower counts) as device operands; a trivial identity plan keeps
    # the trace small while exercising the memo="full" signature plane
    order = jnp.arange(len(jobs), dtype=jnp.int32)
    followers = jnp.zeros((len(jobs),), jnp.int32)
    if memo == "prefix":
        # the prefix-admission step adds the fork operands on top of the
        # memo signature: a checkpoint BANK of lane rows plus the
        # JOB-indexed fork source/depth maps. An all-cold plan (every
        # fork_src -1, a single template bank row) keeps the trace small
        # while exercising the fork-scatter arm the planner drives.
        bank = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[:1], state)
        fork_src = jnp.full((len(jobs),), -1, jnp.int32)
        fork_depth = jnp.zeros((len(jobs),), jnp.int32)
        return Entry(key="batch.stream.step.memo=prefix", fn=step,
                     args=(state, stream, pool_dev, order, followers,
                           None, None, None, None, bank, fork_src,
                           fork_depth),
                     jit_fn=step, donated=(0, 1), state_out=False)
    return Entry(key=f"batch.stream.step.memo={memo}", fn=step,
                 args=(state, stream, pool_dev, order, followers),
                 jit_fn=step, donated=(0, 1), state_out=False)


def _serve_entry() -> Entry:
    import jax
    import jax.numpy as jnp
    from chandy_lamport_tpu.models.workloads import stream_jobs
    from chandy_lamport_tpu.models.workloads import ring_topology
    runner = _batch_runner("sync")
    jobs = stream_jobs(ring_topology(8, tokens=16), 4, seed=5,
                       base_phases=2, max_phases=4)
    pool = runner.pack_jobs(jobs, content_keys=True)
    stream = runner.init_stream(pool, tenants=2,
                                tenant_quota=[0, 2])
    state = runner.init_batch()
    pool_dev = jax.tree_util.tree_map(jnp.asarray, pool)
    step = runner._stream_step(2, 8, False, True)
    # the serve step adds the host-side admission indirection on top of
    # the memo signature: an exec-order array walked only up to the
    # dynamic ``limit`` scalar, plus per-job tenant/arrival/deadline
    # constants feeding the harvest-side books (deadline misses, tenant
    # scatter-add). followers is unused in serve mode (None subtree).
    j = len(jobs)
    order = jnp.arange(j, dtype=jnp.int32)
    tenant_of = jnp.zeros((j,), jnp.int32).at[1::2].set(1)
    arrival_of = jnp.zeros((j,), jnp.int32)
    deadline_of = jnp.full((j,), 64, jnp.int32)
    return Entry(key="batch.stream.step.serve", fn=step,
                 args=(state, stream, pool_dev, order, None,
                       jnp.int32(j), tenant_of, arrival_of, deadline_of),
                 jit_fn=step, donated=(0, 1), state_out=False)


def _graphshard_entry(comm_engine: str) -> Entry:
    import jax
    import numpy as np
    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:4]), ("graph",))
    spec = erdos_renyi(16, 2.5, seed=11, tokens=40)
    gs = GraphShardedRunner(spec, _cfg(), mesh, fixed_delay=2,
                            comm_engine=comm_engine)
    prog = storm_program(gs.topo, phases=2, amount=1,
                         snapshot_phases=staggered_snapshots(gs.topo, 1))
    amounts_s, snap_r = gs.shard_program(np.asarray(prog.amounts),
                                         np.asarray(prog.snap))
    state = gs.init_state()
    return Entry(key=f"graphshard.dispatch.comm={comm_engine}", fn=gs._run,
                 args=(state, gs.stopo_device(), (amounts_s, snap_r)),
                 axis_names=frozenset({"graph"}), state_out=False)


def _pallas_entry(which: str) -> Entry:
    import functools
    import numpy as np
    import jax.numpy as jnp
    from chandy_lamport_tpu.kernels import queue, segment
    e, c, n = 8, 8, 8
    if which == "queue_step":
        fn = functools.partial(queue.queue_step, capacity=c, interpret=True)
        args = (jnp.zeros((e, c), jnp.int32), jnp.zeros((e, c), jnp.int32),
                jnp.zeros((e,), jnp.int32), jnp.zeros((e,), jnp.int32),
                jnp.int32(1), jnp.asarray(np.arange(e, dtype=np.int32)))
        return Entry(key="pallas.queue_step", fn=fn, args=args,
                     state_out=False)
    if which == "sum_segments":
        fn = functools.partial(segment.sum_segments, interpret=True)
        args = (jnp.zeros((e,), jnp.int32),
                jnp.asarray(np.arange(n, dtype=np.int32)),
                jnp.asarray(np.arange(1, n + 1, dtype=np.int32)))
        return Entry(key="pallas.sum_segments", fn=fn, args=args,
                     state_out=False)
    raise KeyError(which)


def iter_entry_builders(mode: str = "full"):
    """Yield (key, builder) pairs for the requested mode.

    full — the whole knob matrix: exact tick {cascade,wave,fold} x
    queue_engine {gather,mask} x kernel_engine {xla,pallas} x faults x
    trace (fold skips faulted arms: the specification form refuses the
    fault engine), the sync tick over the same engine arms, the loop/
    inject entries, both storm schedulers, the stream step (plain, under
    memo="full" — which adds the rolling state-signature plane — and
    under serve=True, which adds the bounded exec-order admission plus
    deadline/tenant harvest books), both graphshard comm engines, the
    Pallas kernels under interpret, and the one-kernel-megatick arms
    (fused impl x queue x faults on run_ticks, fused drain, and the
    split-kernel twins that anchor the hbm_model_bytes comparison —
    plus the ISSUE-16 tiled-state arms and the un-refused supervised/
    traced production arms with their own megasplit anchors).

    fast — one arm per engine axis on the same tiny graphs: enough for
    tier-1 to prove the audit machinery against live traces without
    paying for the matrix (the full sweep is the slow-marked test and
    the default CLI run).
    """
    if mode == "fast":
        picks = [
            ("tick.cascade.q=gather.k=xla.f=0.t=0",
             lambda: _tick_entry("cascade", "gather", "xla", False, False)),
            ("tick.wave.q=mask.k=xla.f=0.t=0",
             lambda: _tick_entry("wave", "mask", "xla", False, False)),
            ("tick.cascade.q=gather.k=pallas.f=0.t=0",
             lambda: _tick_entry("cascade", "gather", "pallas", False,
                                 False)),
            ("sync.q=gather.k=xla.f=0.t=0",
             lambda: _sync_entry("gather", "xla", False, False)),
            ("pallas.queue_step", lambda: _pallas_entry("queue_step")),
            ("tick.fused.cascade.q=gather.f=0.run_ticks",
             lambda: _fused_entry("cascade", "gather", False, "run_ticks")),
        ]
        yield from picks
        return

    for impl in ("cascade", "wave", "fold"):
        for qe in ("gather", "mask"):
            for ke in ("xla", "pallas"):
                for faults in (False, True):
                    if impl == "fold" and faults:
                        continue  # specification form refuses the adversary
                    for trace in (False, True):
                        key = (f"tick.{impl}.q={qe}.k={ke}."
                               f"f={int(faults)}.t={int(trace)}")
                        yield key, (lambda i=impl, q=qe, k=ke, f=faults,
                                    t=trace: _tick_entry(i, q, k, f, t))
    for qe in ("gather", "mask"):
        for ke in ("xla", "pallas"):
            for faults in (False, True):
                for trace in (False, True):
                    key = f"sync.q={qe}.k={ke}.f={int(faults)}.t={int(trace)}"
                    yield key, (lambda q=qe, k=ke, f=faults, t=trace:
                                _sync_entry(q, k, f, t))
    # the one-kernel-megatick arms (kernels/megatick.py): every fused
    # impl x queue-engine x adversary combination on the multi-tick
    # surface, the drain surface on the cascade/gather diagonal, plus
    # the split-kernel twins whose hbm_model_bytes the fused arms must
    # halve (ISSUE-14 acceptance: fused ceiling <= 50% of split)
    for impl in ("cascade", "wave"):
        for qe in ("gather", "mask"):
            for faults in (False, True):
                key = f"tick.fused.{impl}.q={qe}.f={int(faults)}.run_ticks"
                yield key, (lambda i=impl, q=qe, f=faults:
                            _fused_entry(i, q, f, "run_ticks"))
    for faults in (False, True):
        yield f"tick.fused.cascade.q=gather.f={int(faults)}.drain", (
            lambda f=faults: _fused_entry("cascade", "gather", f, "drain"))
        yield f"tick.megasplit.cascade.q=gather.f={int(faults)}.run_ticks", (
            lambda f=faults: _fused_entry("cascade", "gather", f,
                                          "run_ticks", fused="off"))
    # ISSUE-16 arms: the TILED fused layout (rings stream HBM<->VMEM,
    # megatick.RingStream) on both impls and both adversary settings,
    # plus the un-refused production arms — supervisor and flight
    # recorder in-kernel — each with its megasplit twin so the tiled/
    # supervised hbm_model_bytes ratios have same-config anchors
    # (tools/analyze --cost prints the cross-check rows)
    for impl in ("cascade", "wave"):
        for faults in (False, True):
            key = f"tick.fused.tiled.{impl}.q=gather.f={int(faults)}.run_ticks"
            yield key, (lambda i=impl, f=faults:
                        _fused_entry(i, "gather", f, "run_ticks", tile="on"))
    yield "tick.fused.tiled.cascade.q=gather.f=0.drain", (
        lambda: _fused_entry("cascade", "gather", False, "drain", tile="on"))
    for sup, tr in ((True, False), (False, True), (True, True)):
        tag = ".".join([t for t, on in (("sup", sup), ("tr", tr)) if on])
        yield f"tick.fused.{tag}.cascade.q=gather.f=0.run_ticks", (
            lambda s=sup, t=tr: _fused_entry(
                "cascade", "gather", False, "run_ticks",
                supervised=s, traced=t))
        yield f"tick.megasplit.{tag}.cascade.q=gather.f=0.run_ticks", (
            lambda s=sup, t=tr: _fused_entry(
                "cascade", "gather", False, "run_ticks", fused="off",
                supervised=s, traced=t))
    yield "tick.fused.tiled.sup.cascade.q=gather.f=0.run_ticks", (
        lambda: _fused_entry("cascade", "gather", False, "run_ticks",
                             tile="on", supervised=True))
    for name, key in (("run_ticks", "tick.run_ticks"),
                      ("drain", "tick.drain_and_flush"),
                      ("inject_send", "tick.inject_send"),
                      ("inject_snapshot", "tick.inject_snapshot"),
                      ("sync_drain", "sync.drain_and_flush")):
        yield key, (lambda n=name: _loop_entry(n))
    for scheduler in ("exact", "sync"):
        yield f"batch.storm.{scheduler}", (
            lambda s=scheduler: _storm_entry(s))
    yield "batch.stream.step", _stream_entry
    yield "batch.stream.step.memo=full", (lambda: _stream_entry("full"))
    yield "batch.stream.step.memo=prefix", (lambda: _stream_entry("prefix"))
    yield "batch.stream.step.serve", _serve_entry
    for comm in ("dense", "sparse"):
        yield f"graphshard.dispatch.comm={comm}", (
            lambda c=comm: _graphshard_entry(c))
    for which in ("queue_step", "sum_segments"):
        yield f"pallas.{which}", (lambda w=which: _pallas_entry(w))


# ---------------------------------------------------------------------------
# trace walking


def _sub_jaxprs(value):
    """Yield jaxpr-like objects hiding in an eqn param value."""
    import jax.core  # noqa: F401  (ensures types exist)
    vals = value if isinstance(value, (tuple, list)) else (value,)
    for v in vals:
        if hasattr(v, "eqns"):  # Jaxpr
            yield v
        elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):  # ClosedJaxpr
            yield v.jaxpr


def iter_eqns(jaxpr):
    """Depth-first over every eqn including sub-jaxprs (scan/cond/pjit/
    shard_map/pallas_call bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for pval in eqn.params.values():
            for sub in _sub_jaxprs(pval):
                yield from iter_eqns(sub)


def _axis_names_of(eqn) -> List[str]:
    names: List[str] = []
    for k in _AXIS_PARAM_KEYS:
        if k not in eqn.params:
            continue
        v = eqn.params[k]
        for item in (v if isinstance(v, (tuple, list)) else (v,)):
            if isinstance(item, str):
                names.append(item)
    return names


# ---------------------------------------------------------------------------
# checks


def _check_trace(entry: Entry, closed) -> List[Violation]:
    import jax.numpy as jnp
    import numpy as np
    out: List[Violation] = []
    f64 = i64 = None
    callbacks = set()
    for eqn in iter_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        if prim in HOST_CALLBACK_PRIMS:
            callbacks.add(prim)
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is None:
                continue
            if dtype == jnp.float64 and f64 is None:
                f64 = f"float64 aval in eqn {prim!r}"
            # scalar i64 is exempt: under x64 jax itself materializes
            # weak-typed i64 literals/consts (ref indices, normalization
            # scalars) that lower to constants — only ARRAY-shaped 64-bit
            # lanes cost HBM and signal a real promotion bug. Weak-typed
            # arrays are exempt too: they are Python literals broadcast by
            # vmap/scan batching and adopt the context dtype at every use
            # site, so they cannot promote state.
            if (dtype in (jnp.int64, jnp.uint64) and i64 is None
                    and getattr(aval, "shape", ()) != ()
                    and not getattr(aval, "weak_type", False)):
                i64 = (f"{np.dtype(dtype).name}[{','.join(map(str, aval.shape))}] "
                       f"aval in eqn {prim!r}")
        if prim == "ppermute":
            perm = eqn.params.get("perm", ())
            srcs = [p[0] for p in perm]
            dsts = [p[1] for p in perm]
            if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                out.append(Violation(
                    "ppermute-bijection", entry.key,
                    f"ppermute perm {tuple(perm)} is not a bijection — a "
                    f"duplicated/dropped lane deadlocks the halo ring"))
        for name in _axis_names_of(eqn):
            if name not in entry.axis_names:
                out.append(Violation(
                    "collective-axis", entry.key,
                    f"eqn {prim!r} names axis {name!r}, which is not in "
                    f"this entry's mesh axes {sorted(entry.axis_names)}"))
    if f64:
        out.append(Violation(
            "f64-in-trace", entry.key,
            f"{f64} — the state plan is 32-bit; a float64 anywhere means "
            f"an unintended promotion"))
    if i64:
        out.append(Violation(
            "i64-in-trace", entry.key,
            f"{i64} — unintended 64-bit promotion (x64 is enabled in the "
            f"canonical env precisely so these can't hide)"))
    if callbacks:
        out.append(Violation(
            "host-callback", entry.key,
            f"host callback primitives in a hot path: {sorted(callbacks)} "
            f"— use the device flight recorder, not debug prints"))
    if entry.state_out:
        ok = {jnp.int32, jnp.uint32, jnp.bool_}
        for i, aval in enumerate(closed.out_avals):
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and not any(dtype == d for d in ok):
                out.append(Violation(
                    "state-leaf-dtype", entry.key,
                    f"output leaf {i} has dtype {np.dtype(dtype).name}; "
                    f"state leaves are int32/uint32/bool by plan"))
    consts_bytes = sum(
        int(np.asarray(c).nbytes) for c in closed.consts
        if hasattr(c, "nbytes") or hasattr(c, "shape"))
    if consts_bytes > entry.const_budget:
        out.append(Violation(
            "const-capture", entry.key,
            f"jaxpr captures {consts_bytes} bytes of constants "
            f"(budget {entry.const_budget}) — big captured operands embed "
            f"into the HLO and break remote compilation at scale"))
    return out


def _check_donation(entry: Entry) -> List[Violation]:
    if entry.jit_fn is None or not entry.donated:
        return []
    try:
        text = entry.jit_fn.lower(*entry.args).as_text()
    except Exception as exc:  # pragma: no cover - lowering should not fail
        return [Violation("donation", entry.key,
                          f"could not lower to check donation: {exc}")]
    if "tf.aliasing_output" not in text:
        return [Violation(
            "donation", entry.key,
            f"donate_argnums={entry.donated} declared but the lowering "
            f"shows no aliased outputs — donation silently dropped means "
            f"2x state HBM")]
    return []


def _aval_sig(var) -> str:
    import numpy as np
    aval = getattr(var, "aval", None)
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return "?"
    shape = "x".join(map(str, getattr(aval, "shape", ())))
    return f"{np.dtype(dtype).name}[{shape}]"


def _param_sig(value) -> Optional[str]:
    """Stable signature for value-like eqn params (ints, axis names, perm/
    dimension tuples). Returns None for anything that could embed
    process-specific state (functions, jaxprs — hashed structurally via
    recursion — module paths, tracers)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return repr(value)
    if isinstance(value, (tuple, list)):  # NamedTuple dim-numbers included
        parts = [_param_sig(v) for v in value]
        if any(p is None for p in parts):
            return None
        return "(" + ",".join(parts) + ")"
    try:  # np.dtype / dtype-likes
        import numpy as np
        return np.dtype(value).name
    except Exception:
        return None


def _structure_lines(jaxpr, out: List[str]) -> None:
    for eqn in jaxpr.eqns:
        params = ";".join(
            f"{k}={sig}" for k, sig in sorted(
                (k, _param_sig(v)) for k, v in eqn.params.items())
            if sig is not None)
        out.append(f"{eqn.primitive.name}"
                   f"({','.join(_aval_sig(v) for v in eqn.invars)})"
                   f"->({','.join(_aval_sig(v) for v in eqn.outvars)})"
                   f"{{{params}}}")
        for pval in eqn.params.values():
            for sub in _sub_jaxprs(pval):
                out.append("[")
                _structure_lines(sub, out)
                out.append("]")


def trace_fingerprint(closed) -> str:
    """sha256 of a NORMALIZED structural trace: primitive names, in/out
    aval signatures and value-like params, recursed through sub-jaxprs,
    plus the consts signature. Deliberately NOT the pretty-printed jaxpr
    text — that embeds var names, source annotations and module __file__
    paths, all of which shift across jax releases and invocation styles
    and would make the registry fail on every toolchain bump."""
    import numpy as np
    h = hashlib.sha256()
    lines: List[str] = []
    _structure_lines(closed.jaxpr, lines)
    h.update("\n".join(lines).encode())
    for c in closed.consts:
        a = np.asarray(c)
        h.update(f"{a.shape}:{a.dtype};".encode())
    return h.hexdigest()


REGISTRY_SCHEMA = 2


def load_registry(path: Optional[str] = None):
    """Returns (entries, recorded_jax_version). Reads the schema-2 layout
    ``{"schema": 2, "jax": ..., "entries": {...}}``; a legacy flat
    key->hash dict loads with version None."""
    # resolved at call time so tests can repoint FINGERPRINTS_PATH
    path = path or FINGERPRINTS_PATH
    if not os.path.exists(path):
        return {}, None
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and "entries" in data:
        return dict(data["entries"]), data.get("jax")
    return dict(data), None


def save_registry(entries: Dict[str, str],
                  path: Optional[str] = None) -> None:
    """Write the registry, stamping the jax version it was generated
    under — comparisons are only binding in the same-version environment."""
    import jax
    path = path or FINGERPRINTS_PATH
    payload = {
        "schema": REGISTRY_SCHEMA,
        "jax": jax.__version__,
        "entries": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


# ---------------------------------------------------------------------------
# driver


def audit_entry(entry: Entry, *, registry: Optional[Dict[str, str]] = None,
                check_donation: bool = True):
    """Trace one entry and run every check. Returns (violations, fp)."""
    import jax
    closed = jax.make_jaxpr(entry.fn)(*entry.args)
    violations = _check_trace(entry, closed)
    if check_donation:
        violations.extend(_check_donation(entry))
    fp = trace_fingerprint(closed)
    if registry is not None:
        want = registry.get(entry.key)
        if want is None:
            violations.append(Violation(
                "fingerprint", entry.key,
                "no registered lowering fingerprint — run "
                "`python -m tools.staticcheck --fingerprints-update`"))
        elif want != fp:
            violations.append(Violation(
                "fingerprint", entry.key,
                f"lowering changed: trace fingerprint {fp[:12]}… != "
                f"registered {want[:12]}… — intentional changes must "
                f"regenerate fingerprints.json in the same commit"))
    return violations, fp


def audit(mode: str = "full", *, check_fingerprints: bool = True,
          update_fingerprints: bool = False,
          keys: Optional[Sequence[str]] = None):
    """Run the jaxpr plane. Returns (violations, audited_keys, fingerprints).

    ``update_fingerprints`` re-registers every traced entry instead of
    comparing (fast mode updates only the subset it traces). Registered
    fingerprints are only binding when the running jax matches the version
    the registry was generated under — the structural hash is normalized,
    but a toolchain bump can still legitimately change lowerings, so the
    comparison is skipped (with a note) rather than failing spuriously."""
    global _LAST_REGISTRY_NOTE
    ensure_env()
    _LAST_REGISTRY_NOTE = None
    registry = None
    if check_fingerprints and not update_fingerprints:
        import jax
        entries, recorded_jax = load_registry()
        if recorded_jax is not None and recorded_jax != jax.__version__:
            _LAST_REGISTRY_NOTE = (
                f"fingerprint registry was generated under jax "
                f"{recorded_jax} but this run is jax {jax.__version__}; "
                f"comparison skipped — run --fingerprints-update to re-pin")
        else:
            registry = entries
    violations: List[Violation] = []
    audited: List[str] = []
    fresh: Dict[str, str] = {}
    for key, build in iter_entry_builders(mode):
        if keys is not None and key not in keys:
            continue
        try:
            entry = build()
        except Exception as exc:
            violations.append(Violation(
                "entry-build", key,
                f"could not construct the audited entry: "
                f"{type(exc).__name__}: {exc}"))
            continue
        vs, fp = audit_entry(entry, registry=registry)
        violations.extend(vs)
        audited.append(key)
        fresh[key] = fp
    if update_fingerprints:
        merged, _ = load_registry()
        merged.update(fresh)
        save_registry(merged)
    return violations, audited, fresh
