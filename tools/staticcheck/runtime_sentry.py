"""Plane ``runtime``: the runtime contract sentry over the knob matrix.

Static planes can't see dispatch-time behavior: a retrace that only
happens when the serve queue reorders, a host sync snuck into the
stream loop, a numpy operand silently uploaded every step. This plane
RUNS the engine — tiny shapes, one row per engine-knob combination —
twice per row: a warmup pass that compiles and caches every jitted
step, then a steady-state pass under utils/guards.RuntimeGuards
(``jax.transfer_guard("disallow")`` + ``jax.checking_leaks`` + the
compile-event counter), asserting the vectorized-MCMC discipline the
loops claim (PAPERS.md): ZERO compiles after warmup and ZERO transfers
outside the named sites below.

Allowlisting is BY SITE, not global: each row declares exactly which
named transfer sites (utils/guards guarded_get/guarded_put/relaxed
call sites) may fire in steady state. A new sync point in a loop shows
up as an un-allowlisted site name (or, if it bypasses the site helpers
entirely, as an XlaRuntimeError from the transfer guard) and fails
``python -m tools.staticcheck --plane runtime`` with the row and site
named.

Rows (full mode): stream {sync,exact} x memo {off,admit,full} + serve
{edf,fifo} + one graphshard storm arm + three fused-megatick arms
(kernel_engine=pallas, fused_tick=on: a plain stream arm, a SUPERVISED
stream arm with the in-kernel deadline supervisor armed, and a fused
serve arm over the exact scheduler — the steady-state loops dispatch
the one-kernel megatick, proving the fused paths add no host sync or
retrace) + one fleet.worker arm (the HA fleet's in-process serve loop
over the WAL spool: warm on one spool, steady on a FRESH spool with
same-shape different-content requests, so every singleton pool re-uses
the warm executable — the lease/renew/commit bookkeeping is host-side
by design and runs outside the armed region, but the per-request
execution must add zero compiles and no sites beyond the stream
allowlist). Fast mode keeps one row per loop family for tier-1.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from tools.staticcheck import Violation
from tools.staticcheck.jaxpr_audit import ensure_env

# the per-row transfer-site allowlists — THE declarative contract this
# plane enforces. Sites are defined at the guarded_get/guarded_put/
# relaxed call sites in parallel/batch.py, serving/server.py.
STREAM_SITES: FrozenSet[str] = frozenset({
    "stream-carry-upload",         # one bulk h2d per run (init carry)
    "stream-termination-scalars",  # one d2h of (jobs_done, steps)/step
    "memo-fastforward",            # memo=full: host signature watch
})
SERVE_SITES: FrozenSet[str] = frozenset({
    "serve-carry-upload",          # one bulk h2d per run (init carry)
    "serve-admission-order",       # exec-order rewrite, one put/step
    "serve-admission-limit",       # admissible-prefix scalar, one/step
    "serve-progress-scalars",      # the one sync point per step
})
GRAPHSHARD_SITES: FrozenSet[str] = frozenset()


def _topo():
    from chandy_lamport_tpu.models.workloads import ring_topology
    return ring_topology(8, tokens=16)


def _runner(scheduler: str, memo: str, guards, cfg=None, **knobs):
    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    return BatchedRunner(
        _topo(),
        SimConfig.for_workload(snapshots=2, max_recorded=32, **(cfg or {})),
        make_fast_delay("hash", 7), 2, scheduler=scheduler, megatick=2,
        memo=memo, guards=guards, **knobs)


def _check_books(key: str, books: dict, allowed: FrozenSet[str],
                 steps: int) -> List[Violation]:
    out: List[Violation] = []
    if books["compiles"]:
        out.append(Violation(
            "runtime-retrace", key,
            f"{books['compiles']} compile event(s) in the steady-state "
            f"pass ({steps} step(s)) after warmup — the step retraced "
            f"(new shapes, new static args, or a rebuilt jit)"))
    bad = sorted(set(books["transfers"]) - allowed)
    if bad:
        out.append(Violation(
            "runtime-transfer", key,
            f"un-allowlisted transfer site(s) fired in steady state: "
            f"{', '.join(bad)} — add the site to runtime_sentry's row "
            f"allowlist only if the sync is intentional"))
    return out


def _stream_row(key: str, scheduler: str, memo: str, cfg=None,
                **knobs) -> Tuple[List[Violation], int]:
    from chandy_lamport_tpu.models.workloads import stream_jobs
    from chandy_lamport_tpu.utils.guards import RuntimeGuards

    guards = RuntimeGuards()
    runner = _runner(scheduler, memo, guards, cfg=cfg, **knobs)
    jobs = stream_jobs(_topo(), 6, seed=5, base_phases=2, max_phases=4,
                       dup_rate=0.5 if memo != "off" else 0.0)
    pool = runner.pack_jobs(jobs,
                            content_keys=True if memo != "off" else None)
    runner.run_stream(pool, stretch=2, drain_chunk=8)      # warmup
    guards.reset()
    _, stream = runner.run_stream(pool, stretch=2, drain_chunk=8)
    import jax
    steps = int(jax.device_get(stream.steps))
    return _check_books(key, guards.books(), STREAM_SITES, steps), steps


def _serve_row(key: str, policy: str, scheduler: str = "sync",
               **knobs) -> Tuple[List[Violation], int]:
    from chandy_lamport_tpu.models.workloads import serve_workload
    from chandy_lamport_tpu.serving.executables import ExecutableCache
    from chandy_lamport_tpu.serving.server import serve_run
    from chandy_lamport_tpu.utils.guards import RuntimeGuards

    guards = RuntimeGuards()
    runner = _runner(scheduler, "off", guards, **knobs)
    reqs = serve_workload(_topo(), 6, seed=17, rate=2.0, tenants=2,
                          max_phases=6)
    cache = ExecutableCache(None)  # shared: second run hits memory plane
    serve_run(runner, reqs, policy=policy, stretch=2, drain_chunk=8,
              exec_cache=cache)                            # warmup
    guards.reset()
    _, _, report = serve_run(runner, reqs, policy=policy, stretch=2,
                             drain_chunk=8, exec_cache=cache)
    steps = int(report["steps"])
    vs = _check_books(key, guards.books(), SERVE_SITES, steps)
    if report["warmup_source"] != "memory":
        vs.append(Violation(
            "runtime-retrace", key,
            f"steady-state serve did not reuse the warm executable "
            f"(warmup_source={report['warmup_source']!r})"))
    return vs, steps


def _fleet_row(key: str) -> Tuple[List[Violation], int]:
    import os
    import tempfile

    from chandy_lamport_tpu.core.spec import (
        PassTokenEvent, SnapshotEvent, TickEvent)
    from chandy_lamport_tpu.models.workloads import ServeRequest
    from chandy_lamport_tpu.serving.fleet import worker_serve
    from chandy_lamport_tpu.serving.spool import AdmissionSpool
    from chandy_lamport_tpu.utils.guards import RuntimeGuards

    def reqs(tokens0):
        # same event structure (one singleton-pool shape, so the steady
        # pass reuses the warm executable) but different token payloads
        # (different digests, so the shared summary cache cannot answer
        # and the dispatch path actually runs)
        return [ServeRequest(
            job=j, arrival_step=j, tenant=0, priority=1,
            deadline_step=j + 64,
            events=[PassTokenEvent(src="N1", dest="N2", tokens=tokens0 + j),
                    SnapshotEvent(node_id="N3"), TickEvent(4)])
            for j in range(3)]

    guards = RuntimeGuards()
    runner = _runner("sync", "off", guards)
    with tempfile.TemporaryDirectory() as d:
        warm = AdmissionSpool(os.path.join(d, "warm.jsonl"))
        for r in reqs(1):
            warm.admit(r)
        worker_serve("sentry-warm", warm, runner, lease_limit=2,
                     max_wall_s=120)                        # warmup
        guards.reset()
        steady = AdmissionSpool(os.path.join(d, "steady.jsonl"))
        for r in reqs(11):
            steady.admit(r)
        books = worker_serve("sentry", steady, runner, lease_limit=2,
                             max_wall_s=120)
    served = int(books["served"])
    vs = _check_books(key, guards.books(), STREAM_SITES, served)
    if books["cache_served"] or served != 3:
        vs.append(Violation(
            "runtime-retrace", key,
            f"steady-state fleet pass did not dispatch every request "
            f"(served={served}, cache_served={books['cache_served']}) — "
            f"the row proved nothing about the worker's execution path"))
    return vs, served


def _graphshard_row(key: str) -> Tuple[List[Violation], int]:
    import numpy as np
    from jax.sharding import Mesh
    import jax
    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi, staggered_snapshots, storm_program)
    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner
    from chandy_lamport_tpu.utils.guards import RuntimeGuards

    guards = RuntimeGuards()
    topo = erdos_renyi(16, 2.5, seed=11, tokens=40)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("graph",))
    gs = GraphShardedRunner(
        topo, SimConfig.for_workload(snapshots=2, max_recorded=32), mesh,
        axis="graph", fixed_delay=2, guards=guards)
    prog = storm_program(gs.topo, phases=2, amount=1,
                         snapshot_phases=staggered_snapshots(gs.topo, 1))
    gs.run_storm(gs.init_state(), prog.amounts, prog.snap)  # warmup
    guards.reset()
    gs.run_storm(gs.init_state(), prog.amounts, prog.snap)
    return _check_books(key, guards.books(), GRAPHSHARD_SITES, 1), 1


def iter_rows(mode: str = "full"):
    """Yield (key, thunk) per sentry row (jaxpr_audit builder idiom)."""
    if mode == "fast":
        rows = [
            ("stream.sync.memo=off",
             lambda: _stream_row("stream.sync.memo=off", "sync", "off")),
            ("stream.sync.memo=full",
             lambda: _stream_row("stream.sync.memo=full", "sync", "full")),
            ("serve.policy=edf",
             lambda: _serve_row("serve.policy=edf", "edf")),
        ]
    else:
        rows = [
            (f"stream.{sch}.memo={memo}",
             lambda sch=sch, memo=memo: _stream_row(
                 f"stream.{sch}.memo={memo}", sch, memo))
            for sch in ("sync", "exact")
            for memo in ("off", "admit", "full")
        ] + [
            (f"serve.policy={pol}",
             lambda pol=pol: _serve_row(f"serve.policy={pol}", pol))
            for pol in ("edf", "fifo")
        ] + [
            ("graphshard.storm",
             lambda: _graphshard_row("graphshard.storm")),
            # the one-kernel megatick under the armed loop: the exact
            # stream's drain dispatches the fused Pallas kernel
            # (interpret mode here) — same site allowlist as every other
            # stream row, so any fused-path host sync fails loudly
            ("stream.exact.fused",
             lambda: _stream_row("stream.exact.fused", "exact", "off",
                                 kernel_engine="pallas", fused_tick="on")),
            # the SUPERVISED fused arm: deadline arithmetic and retry
            # re-initiation run inside the kernel (ISSUE-16 lifted the
            # production refusal) — an armed supervisor must add no host
            # sync or per-step retrace over the unsupervised row
            ("stream.exact.fused.sup",
             lambda: _stream_row(
                 "stream.exact.fused.sup", "exact", "off",
                 cfg={"snapshot_timeout": 5, "snapshot_retries": 2},
                 kernel_engine="pallas", fused_tick="on")),
            # the fused SERVE step: the online server's steady-state loop
            # dispatches the same fused drain through the exact scheduler
            # — same serve-site allowlist, so the fused path may not add
            # admission-loop syncs beyond the declared per-step scalars
            ("serve.edf.fused",
             lambda: _serve_row("serve.edf.fused", "edf",
                                scheduler="exact", kernel_engine="pallas",
                                fused_tick="on")),
            # the HA fleet's worker loop (serving/fleet.py) in-process:
            # singleton pools over the WAL spool must reuse the warm
            # executable across requests and add no sync beyond the
            # stream sites — the WAL's own fsync bookkeeping is host-side
            # and runs outside the armed run_stream region by design
            ("fleet.worker",
             lambda: _fleet_row("fleet.worker")),
        ]
    return rows


def audit(mode: str = "full", *, keys: Optional[Sequence[str]] = None):
    """Run the sentry. Returns (violations, audited_keys, steps_by_key)."""
    ensure_env()
    violations: List[Violation] = []
    audited: List[str] = []
    steps_by_key: Dict[str, int] = {}
    for key, run in iter_rows(mode):
        if keys is not None and key not in keys:
            continue
        try:
            vs, steps = run()
        except Exception as exc:
            violations.append(Violation(
                "runtime-transfer", key,
                f"guarded steady-state pass raised "
                f"{type(exc).__name__}: {exc} — an implicit transfer or "
                f"tracer leak inside the armed loop"))
            audited.append(key)
            continue
        violations.extend(vs)
        audited.append(key)
        steps_by_key[key] = steps
    return violations, audited, steps_by_key
