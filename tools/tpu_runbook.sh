#!/bin/bash
# One-shot TPU measurement pipeline for a round: run when the device tunnel
# is up. Appends everything to /tmp/runbook_out/ and BASELINE_MEASURED.jsonl.
#
#   1. headline bench (hash delay, derived capacities)
#   2. op-level tick profile (tools/profile_tick.py)
#   3. the BASELINE.md config ladder, sync + exact schedulers
#   4. max-batch probe at the 1M-instance north-star config (ring-10)
#
# Usage: bash tools/tpu_runbook.sh [outdir]
set -u
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-/tmp/runbook_out}"
mkdir -p "$OUT"
cd "$ROOT"

echo "=== 1. bench ==="
# inner --timeout < outer timeout, so bench's own multi-attempt fallback
# chain (hang watchdog -> auto -> cpu) can actually run
timeout 1200 python bench.py --repeats 2 --timeout 300 \
    2>"$OUT/bench_plain.err" | tee "$OUT/bench_plain.json"
tail -5 "$OUT/bench_plain.err"

echo "=== 2. tick profile ==="
timeout 900 python tools/profile_tick.py --out "$OUT/tickprof" \
    > "$OUT/profile.txt" 2>"$OUT/profile.err"
cat "$OUT/profile.txt"

echo "=== 3. ladder (sync + exact) ==="
# outer bound must cover the worst case: 8 configs x (hung default attempt
# + cpu fallback) x 600s inner = 9600s; 10800 leaves headroom
timeout 10800 python tools/ladder.py --scheduler both --timeout 600 \
    > "$OUT/ladder.jsonl" 2>"$OUT/ladder.err"
cat "$OUT/ladder.jsonl"

echo "=== 4. maxbatch (ring-10 north-star config) ==="
timeout 3600 python tools/maxbatch.py --graph ring --nodes 10 \
    --max-snapshots 2 --start 4096 > "$OUT/maxbatch.json" 2>"$OUT/maxbatch.err"
cat "$OUT/maxbatch.json"

echo "=== runbook done; artifacts in $OUT ==="
