#!/usr/bin/env python
"""Randomized full-state differential sweep: wave vs cascade exact ticks.

Deeper than the CI battery (tests/test_wave.py): N random configs across
graph families (ring/ER/scale-free/complete), samplers (hash per-lane
streams, fixed), window/record dtypes, batch widths, and snapshot
schedules including same-phase pileups (many same-tick markers per
destination — the wave's hardest interleaving). Every DenseState field
must be bit-equal between the two formulations, including the ring
planes, the shared log, and the delay sampler's stream position.

Usage: JAX_PLATFORMS=cpu python tools/wave_sweep.py [--cases N] [--seed S]
Exit 0 iff every case matches. Semantics compared: the reference fold,
/root/reference equivalent sim.go:71-95 + node.go:149-185.
"""

from __future__ import annotations

import argparse
import os
import random
import sys

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--cases", type=int, default=16)
    p.add_argument("--seed", type=int, default=9000)
    args = p.parse_args()

    # the differential is platform-independent; run it on CPU and stay off
    # the shared TPU tunnel (this image's plugin overrides JAX_PLATFORMS,
    # so the env var alone is not enough — soak.py does the same)
    jax.config.update("jax_platforms", "cpu")

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.core.state import DenseTopology
    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        ring_topology,
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import FixedJaxDelay, HashJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.compare import dense_state_mismatches
    from chandy_lamport_tpu.utils.fixtures import TopologySpec

    ok = bad = 0
    for case in range(args.cases):
        rng = random.Random(args.seed + case)
        kind = rng.choice(["ring", "er", "sf", "dense"])
        n = rng.randrange(6, 48)
        if kind == "ring":
            spec = ring_topology(n, tokens=80)
        elif kind == "er":
            spec = erdos_renyi(n, rng.uniform(2.0, 5.0), seed=case, tokens=80)
        elif kind == "sf":
            spec = scale_free(max(n, 8), 2, seed=case, tokens=80)
        else:
            m = rng.randrange(4, 9)
            spec = TopologySpec(
                [(f"N{i}", 300) for i in range(m)],
                sorted((f"N{i}", f"N{j}") for i in range(m)
                       for j in range(m) if i != j))
        S = rng.choice([2, 4, 8])
        cfg = SimConfig(max_snapshots=S,
                        queue_capacity=rng.choice([16, 24, 48]),
                        max_recorded=128,
                        window_dtype=rng.choice(["int32", "uint16"]),
                        record_dtype=rng.choice(["int32", "int16"]))
        delay = (HashJaxDelay(seed=rng.randrange(1 << 20)) if case % 3
                 else FixedJaxDelay(rng.randrange(1, 6)))
        B = rng.choice([2, 4, 8])
        phases = rng.randrange(4, 10)
        # ONE schedule decided before the impl loop (drawing it per impl
        # compares different workloads — the bug a first draft of this
        # sweep had)
        topo = DenseTopology(spec)
        k = rng.randrange(1, S + 1)
        sched = ([(0, i % topo.n) for i in range(k)] if case % 2
                 else staggered_snapshots(topo, k, max_phases=phases))
        prog = storm_program(topo, phases=phases, amount=2,
                             snapshot_phases=sched)
        outs = []
        for impl in ("cascade", "wave"):
            r = BatchedRunner(spec, cfg, delay, batch=B, scheduler="exact",
                              exact_impl=impl)
            outs.append(jax.device_get(r.run_storm(r.init_batch(), prog)))
        a, b = outs
        mismatch = dense_state_mismatches(a, b)
        if mismatch:
            bad += 1
            print(f"case {case}: MISMATCH {sorted(mismatch)} kind={kind} "
                  f"S={S} B={B} k={k}", flush=True)
        else:
            ok += 1
            print(f"case {case}: ok kind={kind} n={len(spec.nodes)} S={S} "
                  f"B={B} k={k} delay={type(delay).__name__} "
                  f"win={cfg.window_dtype} err={int(np.max(a.error))}",
                  flush=True)
    print(f"wave sweep: {ok} ok, {bad} mismatched")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
